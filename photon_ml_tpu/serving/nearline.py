"""Nearline personalization: re-solve ONE entity's coefficients online.

PAPER.md's GLMix deployment trains per-entity random-effect models offline
and re-trains the whole table on a batch cadence; the serving-side gap is
the window between "the member just clicked" and "the next bulk retrain
ships". This module closes it the way the paper's architecture implies but
never builds: because every per-entity problem is an ISOLATED vmap lane
(the random-effect solvers never couple entities), one entity's
coefficient row can be re-solved online — warm-started from the live
serving table, against a mini-batch of just-arrived events — and swapped
into the serving tables in place, without touching any other entity and
without a model republish.

:class:`NearlineUpdater` consumes a stream of feedback events::

    {"ids": {"<id_name>": "<entity value>"},      # which entity
     "features": {"<shard>": [[col, value], ...]},  # same schema as scoring
     "label": 1.0,                                 # observed response
     "offset": 0.0,                                # optional margin offset
     "weight": 1.0}                                # optional sample weight

accumulates them into per-entity mini-batches, and on a cadence (or an
explicit :meth:`flush`):

1. resolves each entity through the CURRENT engine's host-side lookup
   (entity value -> (bucket, position)); events for entities outside the
   training vocabulary are counted and dropped — the serving table has no
   row to update;
2. maps event features into each entity's LOCAL projected space via the
   bucket's sorted projection row (features the projection never saw are
   dropped and counted: the local design space is pinned at training).
   An event mapping NO in-projection features is dropped whole — as a
   weight-1 zero-design row it would add nothing to the data term while
   the ridge term re-solved the live row toward zero — and an entity
   left with no usable rows keeps its live row untouched;
3. computes each row's RESIDUAL offset host-side — event offset plus the
   fixed-effect margin and every OTHER coordinate's contribution from the
   engine's model — so the re-solve fits exactly the residual the
   training coordinate-descent fit (single-target caveat: contributions
   of coordinates this updater does not manage are read from the engine's
   load-time model);
4. solves the touched entities as one vmapped warm-started mini-problem —
   the SAME ``_re_solver`` executable family training uses, warm-started
   from the LIVE coefficient rows (gathered on device), entity lanes
   padded to a power of two by duplicating the last real lane so steady
   state reuses a handful of traces and the duplicate scatter is
   idempotent;
5. commits through :meth:`ScoringEngine.apply_re_rows` — the whole table
   tuple swaps atomically under the engine's version lock, so a reader
   sees old rows or new rows, never torn state;
6. on a publish cadence, persists the LIVE tables as the next registry
   version via ``publish_version`` (atomic tmp-assemble + rename — a
   hard kill mid-publish leaves the registry serving the previous
   version, never a torn one).

Telemetry: ``serving.nearline.events`` / ``.dropped_events`` /
``.unknown_entities`` / ``.oov_features`` / ``.applies`` / ``.publishes``
counters; ``serving.nearline.solve_ms`` and ``.update_lag_ms`` (event
enqueue -> applied on the serving tables: the time-to-applied-update the
SLO bench reports) histograms.

Fault seams: ``serving.nearline_event`` (event admission) and
``serving.nearline_apply`` (fires at BOTH commit points — the in-memory
table swap and the registry publish — so the chaos test can hard-kill
either hit and prove the registry is never torn).
"""

from __future__ import annotations

import threading
import time
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.models import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.ops.dense import DenseBatch
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optim.factory import OptimizerConfig, build_objective
from photon_ml_tpu.quality import drift as quality_drift
from photon_ml_tpu.serving.batcher import Overloaded
from photon_ml_tpu.serving.engine import BadRequest

_FP_NEARLINE_EVENT = faults.register_point(
    "serving.nearline_event",
    description="nearline feedback-event admission (one submit call)",
)
_FP_NEARLINE_APPLY = faults.register_point(
    "serving.nearline_apply",
    description="nearline commit: in-memory table swap (hit per bucket "
    "apply) and registry publish (hit per publish)",
)


# engine-or-registry resolution, shared with the front ends — resolved
# PER FLUSH, so a hot swap redirects subsequent nearline applies to the
# new engine
from photon_ml_tpu.serving.server import _engine_of  # noqa: E402


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _Pending:
    """One buffered event, resolved against the engine at flush time."""

    __slots__ = ("ids", "features", "label", "offset", "weight", "t_enqueue")

    def __init__(self, ids, features, label, offset, weight):
        self.ids = ids
        self.features = features
        self.label = label
        self.offset = offset
        self.weight = weight
        self.t_enqueue = time.monotonic()


class _HostView:
    """Host-side numpy view of everything the flush path reads per
    engine: the target coordinate's projections + entity placement, and
    the OTHER coordinates' state for residual-offset computation. Built
    at updater construction and rebuilt ON THE FLUSH THREAD after a hot
    swap — never on a request path. The LIVE target coefficients are
    deliberately NOT here — they are gathered on device at solve time so
    the warm start always sees the newest rows.

    Non-target RANDOM-EFFECT tables are fetched lazily, one bucket on
    first use, and only when an event actually carries that coordinate's
    id and features — a single-RE-coordinate model (the common GLMix
    shape) never pays the host gather; a multi-coordinate model pays it
    per bucket actually referenced, not the whole model. Residuals read
    that coordinate's LOAD-TIME table: a second updater targeting it
    would not be visible here (single-target semantics)."""

    def __init__(self, engine, id_name: str):
        self.engine = engine
        self.slot = engine.re_slot_for(id_name)
        _name, self.lookup, self.entity_bucket, self.entity_pos = (
            engine.re_host(self.slot)
        )
        target = None
        self.others: list[tuple] = []
        for name, sub in engine.model.models.items():
            if isinstance(sub, RandomEffectModel) and sub.id_name == id_name:
                target = sub
            elif isinstance(sub, FixedEffectModel):
                # FE vectors are small and replicated: eager is fine
                self.others.append(
                    ("fixed", sub.shard_name, np.asarray(sub.coefficients))
                )
            elif isinstance(sub, RandomEffectModel):
                # the engine already materialized this coordinate's
                # value->code lookup + placement at load: reuse it rather
                # than rebuilding an O(E) dict per view construction
                _oname, olookup, oebkt, oepos = engine.re_host(
                    engine.re_slot_for(sub.id_name)
                )
                self.others.append(
                    (
                        "re",
                        sub.shard_name,
                        sub.id_name,
                        olookup,
                        oebkt,
                        oepos,
                        sub.buckets,
                        {},  # bucket index -> fetched (proj, coef)
                    )
                )
        if target is None:
            raise BadRequest(
                f"engine model has no random-effect coordinate keyed by "
                f"id '{id_name}'"
            )
        self.shard_name = target.shard_name
        self.projections = [np.asarray(bm.projection) for bm in target.buckets]
        self.local_dims = [p.shape[1] for p in self.projections]

    @staticmethod
    def _other_bucket(buckets, cache: dict, b: int):
        got = cache.get(b)
        if got is None:
            bm = buckets[b]
            got = (np.asarray(bm.projection), np.asarray(bm.coefficients))
            cache[b] = got
        return got

    def residual_offset(self, ev: _Pending) -> float:
        """Event offset + every non-target coordinate's margin for this
        event's features — the residual the target re-solve fits."""
        total = ev.offset
        for other in self.others:
            if other[0] == "fixed":
                _kind, shard, w = other
                for col, val in ev.features.get(shard, ()):
                    if 0 <= col < w.shape[0]:
                        total += float(w[col]) * val
            else:
                (_kind, shard, oid, lookup, ebkt, epos, buckets, cache) = other
                feats = ev.features.get(shard)
                if not feats:
                    continue
                value = ev.ids.get(oid)
                code = lookup.get(str(value), -1) if value is not None else -1
                if code < 0:
                    continue
                proj, coef = self._other_bucket(
                    buckets, cache, int(ebkt[code])
                )
                row_p, row_c = proj[int(epos[code])], coef[int(epos[code])]
                for col, val in feats:
                    k = int(np.searchsorted(row_p, col))
                    if k < row_p.shape[0] and row_p[k] == col:
                        total += float(row_c[k]) * val
        return total


class NearlineUpdater:
    """Per-entity online re-solve loop over a stream of feedback events.

    ``source`` is a :class:`ScoringEngine` or :class:`ModelRegistry`;
    the engine is re-resolved at every flush so registry hot swaps take
    effect on the next apply. ``config`` is the per-entity solver config
    (warm-started, so a handful of iterations converges); ``l2`` adds
    the usual random-effect ridge on top of whatever the config carries.

    ``publish_dir`` + ``publish_interval_s`` persist the live tables as
    new registry versions on a cadence (``index_maps`` required then —
    a published version must pin its feature space like any other).
    """

    def __init__(
        self,
        source,
        id_name: Optional[str] = None,
        config: Optional[OptimizerConfig] = None,
        rows_per_solve: int = 32,
        queue_depth: int = 4096,
        flush_interval_s: float = 1.0,
        publish_dir: Optional[str] = None,
        publish_interval_s: float = 30.0,
        index_maps: Optional[Mapping] = None,
    ):
        if rows_per_solve < 1:
            raise ValueError("rows_per_solve must be >= 1")
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self._source = source
        engine = _engine_of(source)
        self.id_name = id_name or engine.re_host(0)[0]
        self.config = config or OptimizerConfig(
            max_iterations=16, tolerance=1e-7
        )
        self.rows_per_solve = int(rows_per_solve)
        self.queue_depth = int(queue_depth)
        self.flush_interval_s = flush_interval_s
        self.publish_dir = publish_dir
        self.publish_interval_s = publish_interval_s
        self._index_maps = index_maps
        if publish_dir is not None and not index_maps:
            raise ValueError(
                "publish_dir needs index_maps: a published version must "
                "pin the training feature space next to its coefficients"
            )
        self._cv = threading.Condition()
        # entity value -> [newest rows_per_solve _Pending events]
        self._buffers: dict[str, list[_Pending]] = {}
        self._pending = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # built EAGERLY (construction happens at attach time, off the
        # request path) so submit() never builds it on an event loop;
        # rebuilt on the flush thread after a hot swap
        self._view: _HostView = _HostView(engine, self.id_name)
        self._applies_since_publish = 0
        self._last_publish = time.monotonic()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "NearlineUpdater":
        with self._cv:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="nearline-updater", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the cadence thread, flushing buffered events first."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    break
                self._cv.wait(timeout=self.flush_interval_s)
            try:
                self.flush()
                self._maybe_publish()
            except Exception:  # noqa: BLE001 — the cadence must survive
                telemetry.counter("serving.nearline.flush_errors").inc()
        try:
            self.flush()  # drain on stop
        except Exception:  # noqa: BLE001
            telemetry.counter("serving.nearline.flush_errors").inc()

    # -- event admission -----------------------------------------------------

    def submit(self, events: Sequence[Mapping]) -> int:
        """Buffer feedback events; returns how many were ACCEPTED
        (events for entities outside the training vocabulary, or with no
        usable features, are counted and dropped — not errors). A
        structurally malformed event raises :class:`BadRequest`; a full
        buffer sheds the whole call with :class:`Overloaded`."""
        faults.fault_point(_FP_NEARLINE_EVENT)
        # the CACHED view: rebuilding here would put a host gather on the
        # submit path (the asyncio front end's event loop), so the
        # unknown-entity pre-check only runs while the view matches the
        # live engine. After a hot swap, events are accepted unchecked and
        # flush() — which rebuilds the view on its own thread — resolves
        # them authoritatively; otherwise entities that exist only in the
        # NEW model would be dropped against the stale vocabulary forever.
        view = self._view
        check_known = view.engine is _engine_of(self._source)
        parsed = []
        dropped = 0
        for i, ev in enumerate(events):
            if not isinstance(ev, Mapping):
                raise BadRequest(f"event {i} must be an object")
            ids = ev.get("ids")
            if not isinstance(ids, Mapping) or self.id_name not in ids:
                raise BadRequest(
                    f"event {i}: 'ids' must contain '{self.id_name}'"
                )
            label = ev.get("label")
            if not isinstance(label, (int, float)):
                raise BadRequest(f"event {i}: 'label' must be a number")
            feats = ev.get("features") or {}
            if not isinstance(feats, Mapping):
                raise BadRequest(f"event {i}: 'features' must be an object")
            entity = str(ids[self.id_name])
            if check_known and view.lookup.get(entity, -1) < 0:
                telemetry.counter("serving.nearline.unknown_entities").inc()
                dropped += 1
                continue
            features = {}
            for shard, flist in feats.items():
                pairs = []
                for feat in flist or ():
                    if not (
                        isinstance(feat, (list, tuple)) and len(feat) == 2
                    ):
                        raise BadRequest(
                            f"event {i}: features must be [col, value] "
                            "pairs (named features are a scoring-path "
                            "nicety; the feedback log writes ids)"
                        )
                    pairs.append((int(feat[0]), float(feat[1])))
                features[shard] = pairs
            weight = ev.get("weight")
            parsed.append(
                (
                    entity,
                    _Pending(
                        dict(ids), features, float(label),
                        float(ev.get("offset") or 0.0),
                        # an explicit 0 must STAY 0 (a tombstone carrying
                        # no sample weight), so no falsy-or default here
                        1.0 if weight is None else float(weight),
                    ),
                )
            )
        with self._cv:
            if self._pending + len(parsed) > self.queue_depth:
                telemetry.counter("serving.nearline.shed").inc()
                raise Overloaded(
                    f"nearline buffer at capacity: {self._pending} events "
                    f"pending, depth {self.queue_depth}"
                )
            for entity, pending in parsed:
                buf = self._buffers.setdefault(entity, [])
                buf.append(pending)
                if len(buf) > self.rows_per_solve:
                    # keep the NEWEST rows_per_solve events per entity
                    del buf[0]
                else:
                    self._pending += 1
        telemetry.counter("serving.nearline.events").inc(len(parsed))
        if dropped:
            telemetry.counter("serving.nearline.dropped_events").inc(dropped)
        return len(parsed)

    def _view_for(self, engine) -> _HostView:
        view = self._view
        if view is None or view.engine is not engine:
            view = _HostView(engine, self.id_name)
            with self._cv:  # submit threads and the cadence thread race here
                self._view = view
        return view

    # -- the re-solve --------------------------------------------------------

    def flush(self) -> dict:
        """Re-solve and commit every buffered entity's rows against the
        CURRENT engine. Returns ``{"entities", "rows", "applies"}``
        counting what was actually solved and applied.

        Buckets are ISOLATED: one bucket's failure (a solver error, an
        injected fault at the commit seam) requeues that bucket's events
        for the next flush and does not stop the other buckets' applies;
        the first error is re-raised once every bucket has had its turn."""
        with self._cv:
            if not self._buffers:
                return {"entities": 0, "rows": 0, "applies": 0}
            buffers, self._buffers, self._pending = self._buffers, {}, 0
        engine = _engine_of(self._source)
        view = self._view_for(engine)
        t0 = time.monotonic()
        # group touched entities by geometry bucket: each bucket's table
        # has its own [E, K] shape, so each is one vmapped mini-solve
        by_bucket: dict[int, list[tuple[int, str]]] = {}
        for entity in buffers:
            code = view.lookup.get(entity, -1)
            if code < 0:  # engine swapped to a model without this entity
                telemetry.counter("serving.nearline.unknown_entities").inc()
                continue
            by_bucket.setdefault(int(view.entity_bucket[code]), []).append(
                (code, entity)
            )
        loss_name = get_loss(engine.task).name
        obj = build_objective(loss_name, self.config)
        l1 = jnp.float32(
            self.config.regularization.l1_weight(
                self.config.regularization_weight
            )
        )
        applies = 0
        rows_total = 0
        entities_total = 0
        first_error: Optional[Exception] = None
        R = self.rows_per_solve
        for bucket, members in sorted(by_bucket.items()):
            proj = view.projections[bucket]
            local_k = view.local_dims[bucket]
            # per-entity USABLE rows: an event mapping zero in-projection
            # features carries no data about this row — as a weight-1
            # zero-design row the pure ridge term would re-solve the live
            # row toward zero, so such events are dropped and an entity
            # left with no usable rows keeps its live row untouched
            lanes: list[tuple[int, list[tuple]]] = []
            dropped = 0
            for code, entity in members:
                pos = int(view.entity_pos[code])
                proj_row = proj[pos]
                rows = []
                for ev in buffers[entity][-R:]:
                    if ev.weight <= 0:
                        # a weightless row adds nothing to the data term;
                        # like an all-OOV row it would leave the ridge
                        # term free to pull the live row toward zero
                        dropped += 1
                        continue
                    xrow = np.zeros((local_k,), np.float32)
                    mapped = 0
                    for col, val in ev.features.get(view.shard_name, ()):
                        k = int(np.searchsorted(proj_row, col))
                        if k < local_k and proj_row[k] == col:
                            xrow[k] = val
                            mapped += 1
                        else:
                            telemetry.counter(
                                "serving.nearline.oov_features"
                            ).inc()
                    if not mapped:
                        dropped += 1
                        continue
                    rows.append(
                        (xrow, ev.label, view.residual_offset(ev),
                         ev.weight, ev.t_enqueue)
                    )
                if rows:
                    lanes.append((pos, rows))
            if dropped:
                telemetry.counter("serving.nearline.dropped_events").inc(
                    dropped
                )
            if not lanes:
                continue
            n = len(lanes)
            n_pad = _next_pow2(n)
            x = np.zeros((n_pad, R, local_k), np.float32)
            labels = np.zeros((n_pad, R), np.float32)
            offsets = np.zeros((n_pad, R), np.float32)
            weights = np.zeros((n_pad, R), np.float32)
            positions = np.zeros((n_pad,), np.int32)
            lags = []
            for j, (pos, rows) in enumerate(lanes):
                positions[j] = pos
                for r, (xrow, label, offset, weight, t_enq) in enumerate(
                    rows
                ):
                    x[j, r] = xrow
                    labels[j, r] = label
                    offsets[j, r] = offset
                    weights[j, r] = weight
                    lags.append(t_enq)
            # pad entity lanes by DUPLICATING the last real lane: the
            # duplicate solves to the identical row and the double
            # scatter at the same position is idempotent — no lane ever
            # commits a zero-data artifact over a real row
            for j in range(n, n_pad):
                x[j], labels[j] = x[n - 1], labels[n - 1]
                offsets[j], weights[j] = offsets[n - 1], weights[n - 1]
                positions[j] = positions[n - 1]
            try:
                batch = DenseBatch(
                    x=jnp.asarray(x),
                    labels=jnp.asarray(labels),
                    offsets=jnp.asarray(offsets),
                    weights=jnp.asarray(weights),
                )
                # warm start from the LIVE rows (device gather — reflects
                # every previous nearline apply, not the load-time model)
                coef_table = engine.re_tables(view.slot)[bucket][1]
                w0 = coef_table[jnp.asarray(positions)]
                solver = _nearline_solver(self.config, loss_name)
                res, _var = solver(obj, batch, w0, l1, None)
                faults.fault_point(_FP_NEARLINE_APPLY)
                engine.apply_re_rows(
                    view.slot, bucket, positions, res.w, real_rows=n
                )
            except Exception as exc:  # noqa: BLE001 — isolate the bucket
                self._requeue(members, buffers)
                if first_error is None:
                    first_error = exc
                continue
            applies += 1
            entities_total += n
            rows_total += sum(len(rows) for _pos, rows in lanes)
            # labeled events feed the per-version calibration sketch:
            # predicted probability (from the rows just applied) against
            # the observed label. Flush thread, never the request path —
            # one extra fetch per bucket apply. Logistic only: the
            # calibration bins assume probabilities.
            if loss_name == "logistic":
                w_host = telemetry.sync_fetch(
                    res.w, label="nearline.calibration_rows"
                )
                margins = offsets[:n] + np.einsum(
                    "jrk,jk->jr", x[:n], w_host[:n]
                )
                live = weights[:n] > 0
                if live.any():
                    probs = 1.0 / (1.0 + np.exp(-margins[live]))
                    quality_drift.observe_labeled(
                        engine.version, probs, labels[:n][live]
                    )
            now = time.monotonic()
            lag_ms = telemetry.histogram("serving.nearline.update_lag_ms")
            for t in lags:
                lag_ms.observe((now - t) * 1000.0)
        if applies:
            telemetry.histogram("serving.nearline.solve_ms").observe(
                (time.monotonic() - t0) * 1000.0
            )
            telemetry.counter("serving.nearline.applies").inc(applies)
            with self._cv:
                self._applies_since_publish += applies
        if first_error is not None:
            raise first_error
        return {
            "entities": entities_total,
            "rows": rows_total,
            "applies": applies,
        }

    def _requeue(self, members, buffers) -> None:
        """Put a failed bucket's events back at the FRONT of the live
        buffers — they are older than anything submitted since — capped
        to the newest ``rows_per_solve`` per entity, so a transient
        bucket failure retries on the next flush instead of silently
        discarding accepted events."""
        with self._cv:
            for _code, entity in members:
                old = buffers.get(entity)
                if not old:
                    continue
                cur = self._buffers.get(entity, [])
                merged = (old + cur)[-self.rows_per_solve:]
                self._pending += len(merged) - len(cur)
                self._buffers[entity] = merged

    # -- persistence ---------------------------------------------------------

    def _maybe_publish(self) -> None:
        if self.publish_dir is None:
            return
        with self._cv:
            due = (
                self._applies_since_publish > 0
                and time.monotonic() - self._last_publish
                >= self.publish_interval_s
            )
        if due:
            self.publish()

    def publish(self) -> Optional[str]:
        """Persist the engine's LIVE tables (every nearline row swap
        included) as the next registry version. Returns the published
        path, or None when nothing was applied since the last publish."""
        from photon_ml_tpu.serving.registry import publish_version

        if self.publish_dir is None:
            raise ValueError("no publish_dir configured")
        with self._cv:
            if not self._applies_since_publish:
                return None
        engine = _engine_of(self._source)
        faults.fault_point(_FP_NEARLINE_APPLY)
        path = publish_version(
            self.publish_dir,
            engine.current_model(),
            self._publishable_index_maps(),
            extra_metadata={
                "nearline_seq": engine.nearline_seq,
                "nearline_base_version": engine.version,
            },
        )
        with self._cv:
            self._applies_since_publish = 0
            self._last_publish = time.monotonic()
        telemetry.counter("serving.nearline.publishes").inc()
        return path

    def _publishable_index_maps(self):
        """publish_version accepts IndexMaps or name sequences; a plain
        {name: col} mapping (the engine-construction convenience) is
        normalized to its col-ordered name list."""
        from photon_ml_tpu.data.index_map import IndexMap

        out = {}
        for shard, imap in self._index_maps.items():
            if isinstance(imap, Mapping) and not isinstance(imap, IndexMap):
                out[shard] = [
                    name for name, _c in sorted(imap.items(), key=lambda kv: kv[1])
                ]
            else:
                out[shard] = imap
        return out


def _nearline_solver(config: OptimizerConfig, loss_name: str):
    """The vmapped warm-started per-entity solver — the SAME instrumented
    executable family the training coordinate uses (``re_solve``), so
    nearline solves surface in the executable registry next to training's
    and reuse its traces when shapes line up."""
    from photon_ml_tpu.game.coordinates import _re_solver

    return _re_solver(config, loss_name)
