"""Shard-owning serving members: slice a GAME model to one fleet
member's deterministic entity block and serve it from a per-member
engine (ROADMAP item 3 — the serving leg of the fleet story).

Ownership is pure math (``parallel.sharding.member_row_range``): member
``i`` of ``N`` owns the contiguous entity-code block ``[i*E/N,
(i+1)*E/N)`` of every random-effect coordinate, a function of the fleet
size alone — every member and the router derive the SAME map with no
coordination, and a resize is just re-deriving it at the new size.
Fixed-effect vectors are replicated (they are small and every member
must be able to serve the FE-only degraded fallback).

The sliced model keeps the FULL vocab and marks non-owned codes with
bucket ``-1`` in the host placement arrays, so a non-owned entity
contributes exactly 0 on this member (``serving.not_owned_entities``)
— the router's fold over owning members is lossless because the GAME
score is additive and every entity's rows exist on exactly one member.

:class:`ShardMemberSource` is the member's engine source: engines are
keyed by ``(fleet_size, version)`` and swapped through an explicit
stage/commit barrier, so a live resize (or fleet-wide hot swap) keeps
the old slice serving until the router flips — the member tolerates the
mixed-version window by resolving requests pinned to either side.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Mapping, Optional

import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.parallel import sharding as psharding
from photon_ml_tpu.serving.engine import ScoringEngine

_FP_MEMBER_LOAD = faults.register_point(
    "serving.member_load",
    distributed=True,
    description=(
        "a fleet member loading (or re-loading after relaunch/resize) "
        "its entity slice — io action = transient shard read"
    ),
)


class ShardBudgetError(RuntimeError):
    """A member's entity slice does not fit its configured HBM budget —
    a fleet-sizing error (grow the fleet), distinct from model
    corruption."""


def serving_table_bytes(model: GameModel) -> int:
    """Predicted HBM residency of ``model`` served: FE vectors plus
    coefficient + int32 projection per RE bucket (the engine's own
    upload prediction, reusable before any engine exists)."""
    total = 0
    for sub in model.models.values():
        if isinstance(sub, FixedEffectModel):
            total += telemetry.memory.estimate_table_bytes(
                1, np.asarray(sub.coefficients).shape[0]
            )
        elif isinstance(sub, RandomEffectModel):
            for bm in sub.buckets:
                num_e, local_k = np.asarray(bm.coefficients).shape
                total += 2 * telemetry.memory.estimate_table_bytes(
                    num_e, local_k
                )
    return total


def slice_model_for_member(
    model: GameModel, member: int, num_members: int
) -> GameModel:
    """``model`` with every random-effect table cut down to member
    ``member``'s owned entity-code block.

    Per coordinate: owned codes keep their bucket rows (re-packed dense,
    positions renumbered); every other code gets bucket ``-1`` so it
    scores 0 here. Buckets left empty by the cut are dropped (their
    indices renumber with the placement arrays). The vocab stays FULL —
    a non-owned id must resolve to a known code (and count
    ``serving.not_owned_entities``), never masquerade as unseen.
    Indivisible coordinates raise the valid-fleet-sizes listing."""
    out = model
    for name, sub in model.models.items():
        if not isinstance(sub, RandomEffectModel):
            continue
        num_entities = int(len(sub.vocab))
        try:
            lo, hi = psharding.member_row_range(
                num_entities, member, num_members
            )
        except psharding.ElasticPlacementError:
            raise psharding.fleet_size_mismatch(
                num_entities, num_members,
                what=f"slice coordinate '{name}' across the serving fleet",
            ) from None
        entity_bucket = np.asarray(sub.entity_bucket)
        entity_pos = np.asarray(sub.entity_pos)
        new_bucket = np.full(num_entities, -1, np.int32)
        new_pos = np.full(num_entities, -1, np.int32)
        owned = np.zeros(num_entities, bool)
        owned[lo:hi] = True
        new_buckets = []
        for b, bm in enumerate(sub.buckets):
            codes = np.nonzero(owned & (entity_bucket == b))[0]
            if not len(codes):
                continue  # bucket entirely elsewhere; indices renumber
            rows_sel = entity_pos[codes]
            b_new = len(new_buckets)
            new_bucket[codes] = b_new
            new_pos[codes] = np.arange(len(codes), dtype=np.int32)
            new_buckets.append(
                dataclasses.replace(
                    bm,
                    coefficients=np.asarray(bm.coefficients)[rows_sel],
                    projection=np.asarray(bm.projection)[rows_sel],
                    entity_codes=np.asarray(codes, np.int32),
                    variances=(
                        None if bm.variances is None
                        else np.asarray(bm.variances)[rows_sel]
                    ),
                )
            )
        out = out.with_model(
            name,
            dataclasses.replace(
                sub,
                buckets=tuple(new_buckets),
                entity_bucket=new_bucket,
                entity_pos=new_pos,
            ),
        )
    return out


def member_owned_ranges(
    model: GameModel, member: int, num_members: int
) -> dict[str, tuple[int, int]]:
    """``{id_name: [lo, hi)}`` for the fleet-status surface — the code
    block this member serves per random-effect coordinate."""
    out = {}
    for sub in model.models.values():
        if isinstance(sub, RandomEffectModel):
            out[sub.id_name] = psharding.member_row_range(
                int(len(sub.vocab)), member, num_members
            )
    return out


def _restore_member_rows(
    sub: RandomEffectModel,
    sliced: RandomEffectModel,
    coord: str,
    ckpt_dir: str,
    lo: int,
    hi: int,
):
    """Replace the SLICED single-bucket coordinate's coefficients with
    rows ``[lo, hi)`` restored straight off the streamed checkpoint's
    mmap'd shard files (``restore_row_range``) — the member-shard
    complement of ``restore_placed``: no member ever materializes more
    than its own slice. Requires the coordinate's bucket positions to be
    contiguous over the owned block (the streamed-training layout);
    anything else must fail loudly, never read a wrong slice."""
    from photon_ml_tpu.data.model_store import ModelLoadError
    from photon_ml_tpu.game.checkpoint import StreamingCheckpointManager

    if len(sub.buckets) != 1:
        raise ModelLoadError(
            ckpt_dir,
            f"coordinate '{coord}' has {len(sub.buckets)} geometry "
            "buckets; streamed checkpoints hold ONE dense [E, K] table, "
            "so only single-bucket coordinates restore from one",
        )
    pos = np.asarray(sub.entity_pos)[lo:hi]
    if len(pos) and not np.array_equal(
        pos, np.arange(pos[0], pos[0] + len(pos))
    ):
        raise ModelLoadError(
            ckpt_dir,
            f"coordinate '{coord}' bucket positions are not contiguous "
            f"over entity block [{lo}, {hi}) — a member cannot restore "
            "it as one checkpoint row range",
        )
    manager = StreamingCheckpointManager.open_for_restore(ckpt_dir)
    rows = manager.restore_row_range(int(pos[0]), int(pos[0]) + len(pos))
    if rows is None:
        raise ModelLoadError(
            ckpt_dir,
            "no certified streamed checkpoint to restore the member "
            f"slice of coordinate '{coord}' from",
        )
    bm = sliced.buckets[0]
    want = tuple(int(d) for d in np.asarray(bm.coefficients).shape)
    got = tuple(int(d) for d in rows.shape)
    if got != want:
        raise ModelLoadError(
            ckpt_dir,
            f"checkpoint member rows shape {got} does not match "
            f"coordinate '{coord}' slice shape {want}",
        )
    return dataclasses.replace(
        sliced, buckets=(dataclasses.replace(bm, coefficients=rows),)
    )


def load_member_engine(
    model_dir: str,
    member: int,
    fleet_size: int,
    max_batch: int = 64,
    max_row_nnz: int = 128,
    version: Optional[str] = None,
    hbm_budget_bytes: Optional[int] = None,
    re_checkpoints: Optional[Mapping[str, str]] = None,
    warm: bool = True,
) -> ScoringEngine:
    """Build (and by default warm, margins included) the
    :class:`ScoringEngine` serving member ``member``'s slice of the
    model in ``model_dir``.

    ``hbm_budget_bytes`` enforces the whole point of the fleet: the
    member's SLICE must fit the budget (:class:`ShardBudgetError`
    otherwise, naming the fleet sizes that would) even when the full
    model could not. ``re_checkpoints`` (coordinate -> streamed
    checkpoint dir) restores that coordinate's slice straight off the
    checkpoint's shard files — only the owned row range is ever read."""
    import os

    from photon_ml_tpu.data.model_store import (
        ModelLoadError,
        load_feature_index_maps,
        load_game_model,
        load_game_model_metadata,
    )

    faults.fault_point(_FP_MEMBER_LOAD)
    with telemetry.span(
        "serving:member_load", member=member, fleet_size=fleet_size
    ):
        index_maps = load_feature_index_maps(model_dir)
        if index_maps is None:
            raise ModelLoadError(
                os.path.join(model_dir, "feature-indexes"),
                "missing feature-indexes/ — a fleet member cannot pin the "
                "serving feature space, so scores would be silently wrong",
            )
        model = load_game_model(model_dir)
        sliced = slice_model_for_member(model, member, fleet_size)
        for coord, ckpt_dir in (re_checkpoints or {}).items():
            sub = model.models.get(coord)
            cut = sliced.models.get(coord)
            if not isinstance(sub, RandomEffectModel):
                raise ModelLoadError(
                    ckpt_dir,
                    f"re_checkpoints names coordinate '{coord}', which is "
                    "not a random-effect coordinate of the model "
                    f"(has: {sorted(model.models)})",
                )
            lo, hi = psharding.member_row_range(
                int(len(sub.vocab)), member, fleet_size
            )
            sliced = sliced.with_model(
                coord,
                _restore_member_rows(sub, cut, coord, ckpt_dir, lo, hi),
            )
        slice_bytes = serving_table_bytes(sliced)
        if hbm_budget_bytes is not None and slice_bytes > hbm_budget_bytes:
            raise ShardBudgetError(
                f"member {member}/{fleet_size} slice needs {slice_bytes} "
                f"bytes, over the {int(hbm_budget_bytes)}-byte HBM budget "
                f"(full model: {serving_table_bytes(model)} bytes) — grow "
                "the fleet"
            )
        try:
            lineage = (
                load_game_model_metadata(model_dir).get("extra") or {}
            ).get("lineage")
        except (OSError, ValueError):
            lineage = None
        engine = ScoringEngine(
            sliced,
            index_maps=index_maps,
            max_batch=max_batch,
            max_row_nnz=max_row_nnz,
            version=version
            or os.path.basename(os.path.normpath(model_dir)),
        )
        telemetry.gauge("serving.member_slice_bytes").set(slice_bytes)
        if warm:
            engine.warmup(margins=True)
        return engine


class ShardMemberSource:
    """One fleet member's engine source: ``(fleet_size, version)``-keyed
    engines behind a stage/commit barrier.

    ``stage`` loads and warms a new slice WHILE the current one serves
    (resize: the same registry version re-sliced at the new fleet size;
    hot swap: a new version at the current size). ``commit`` flips the
    current pointer and keeps exactly one previous engine — the
    mixed-version window the router pins requests through — evicting
    anything older. ``resolve`` serves a request pinned to either side
    of the barrier; an unknown pin raises ``KeyError`` (the front end
    maps it to a client error and the router retries or degrades).

    The loader is ``loader(fleet_size, version) -> warmed engine``
    (``version=None`` means the registry's newest)."""

    def __init__(
        self,
        loader: Callable[[int, Optional[str]], ScoringEngine],
        member: int,
        fleet_size: int,
    ):
        self._loader = loader
        self.member = int(member)
        self.initial_fleet_size = int(fleet_size)
        self._lock = threading.RLock()
        self._engines: dict[tuple[int, str], ScoringEngine] = {}
        self._current: Optional[tuple[int, str]] = None
        self._previous: Optional[tuple[int, str]] = None

    @property
    def engine(self) -> ScoringEngine:
        with self._lock:
            if self._current is None:
                raise RuntimeError(
                    f"member {self.member}: no committed shard engine"
                )
            return self._engines[self._current]

    @property
    def fleet_size(self) -> int:
        with self._lock:
            if self._current is None:
                return self.initial_fleet_size
            return self._current[0]

    def staged_keys(self) -> list[tuple[int, str]]:
        with self._lock:
            return sorted(self._engines)

    def stage(
        self, fleet_size: int, version: Optional[str] = None
    ) -> tuple[int, str]:
        """Load + warm the ``(fleet_size, version)`` slice without
        touching what currently serves; idempotent per key."""
        fleet_size = int(fleet_size)
        with self._lock:
            if version is not None:
                key = (fleet_size, str(version))
                if key in self._engines:
                    return key
        engine = self._loader(fleet_size, version)
        key = (fleet_size, engine.version)
        with self._lock:
            self._engines.setdefault(key, engine)
        return key

    def commit(self, fleet_size: int, version: str) -> tuple[int, str]:
        """Flip the current pointer to a STAGED key; the previous
        current stays resolvable (one mixed-window slot), everything
        older is evicted."""
        key = (int(fleet_size), str(version))
        with self._lock:
            if key not in self._engines:
                raise KeyError(
                    f"member {self.member}: commit of unstaged "
                    f"{key}; staged: {sorted(self._engines)}"
                )
            if key != self._current:
                self._previous, self._current = self._current, key
            keep = {k for k in (self._current, self._previous) if k}
            for k in list(self._engines):
                if k not in keep:
                    del self._engines[k]
        return key

    def resolve(
        self,
        fleet_size: Optional[int] = None,
        version: Optional[str] = None,
    ) -> ScoringEngine:
        """The engine a request pinned to ``(fleet_size, version)``
        scores on; ``None`` pins default to the current engine's."""
        with self._lock:
            if self._current is None:
                raise RuntimeError(
                    f"member {self.member}: no committed shard engine"
                )
            if fleet_size is None:
                fleet_size = self._current[0]
            fleet_size = int(fleet_size)
            if version is not None:
                engine = self._engines.get((fleet_size, str(version)))
                if engine is None:
                    raise KeyError(
                        f"member {self.member} holds no engine for "
                        f"fleet_size={fleet_size} version={version!r}; "
                        f"staged: {sorted(self._engines)}"
                    )
                return engine
            for key in (self._current, self._previous):
                if key is not None and key[0] == fleet_size:
                    return self._engines[key]
            for key in sorted(self._engines):
                if key[0] == fleet_size:
                    return self._engines[key]
            raise KeyError(
                f"member {self.member} holds no engine for "
                f"fleet_size={fleet_size}; staged: {sorted(self._engines)}"
            )
