"""Online serving: device-resident scoring engine, micro-batching, and a
hot-swappable model registry.

The batch ``cli score`` driver re-reads data, rebuilds index maps, and
re-uploads host numpy on every invocation; GLMix-style models exist to be
served online per member/item (Zhang et al., KDD 2016), and adaptive
micro-batching with latency deadlines is how accelerator-backed prediction
services get throughput (Crankshaw et al., Clipper, NSDI 2017). This
package is the long-lived answer:

- :mod:`photon_ml_tpu.serving.engine` — :class:`ScoringEngine` compiles a
  trained :class:`GameModel` ONCE into device-resident form (coefficient
  tables + entity indices uploaded to HBM at load, after a telemetry
  headroom check) and serves jit-compiled score functions keyed by padded
  batch-size bucket, all warmed at startup so steady state never
  recompiles. Unseen entities fall back to fixed-effect-only scores.
- :mod:`photon_ml_tpu.serving.batcher` — :class:`MicroBatcher` coalesces
  concurrent requests into padded batches under a ``max_delay_ms``
  deadline, with queue-depth admission control (:class:`Overloaded`).
- :mod:`photon_ml_tpu.serving.registry` — :class:`ModelRegistry` watches a
  versioned models directory (manifest-written-last, same certification
  idea as ``game/checkpoint.py``), hot-swaps to the newest valid version
  in the background, and skips past corrupt/partial versions.
- :mod:`photon_ml_tpu.serving.server` — stdlib HTTP endpoints
  (``POST /v1/score``, ``POST /v1/update``, ``GET /healthz``,
  ``GET /metricsz``) plus a stdio JSONL mode so tests and CI can drive
  the service without sockets.
- :mod:`photon_ml_tpu.serving.aio` — :class:`AsyncScoringServer`, the
  same endpoints from ONE asyncio event loop instead of a thread per
  connection (the sustained-load front end; pairs with
  :class:`ContinuousBatcher`, which admits rows into the next in-flight
  device bucket as capacity frees instead of waiting out a deadline).
- :mod:`photon_ml_tpu.serving.shard` — shard-owning fleet members: each
  serving process loads ONLY its deterministic contiguous slice of every
  random-effect table (``slice_model_for_member`` /
  ``load_member_engine``), so the fleet serves models whose entity tables
  exceed any single host's HBM. :class:`ShardMemberSource` stages and
  commits ``(fleet_size, version)``-keyed engines for live resize and
  coordinated hot swap with a mixed-version window.
- :mod:`photon_ml_tpu.serving.router` — :class:`FleetRouter` fans entity
  lookups out to owning members, folds partial margins EXACTLY (the GAME
  score is additive), and degrades to fixed-effect-only scores (counted
  ``serving.degraded_scores``) when a member is unreachable — the fleet
  sheds accuracy, never availability.
- :mod:`photon_ml_tpu.serving.nearline` — :class:`NearlineUpdater`
  consumes (entity, features, label) feedback events and re-solves JUST
  those entities' random-effect coefficient rows online (warm-started
  from the live tables, the training solver's vmap lanes), swapping them
  into the serving tables in place and publishing updated versions on a
  cadence.

With ``ScoringEngine.load(..., mesh=...)`` the random-effect tables are
placed ENTITY-SHARDED across the mesh (``parallel.sharding`` — the same
placement training uses, so sharded training checkpoints restore straight
onto the serving mesh via ``re_checkpoints=``).

Wired to the CLI as ``python -m photon_ml_tpu.cli serve``.
"""

from photon_ml_tpu.serving.aio import AsyncScoringServer  # noqa: F401
from photon_ml_tpu.serving.batcher import (  # noqa: F401
    ContinuousBatcher,
    Draining,
    MicroBatcher,
    Overloaded,
)
from photon_ml_tpu.serving.engine import BadRequest, ScoringEngine  # noqa: F401
from photon_ml_tpu.serving.nearline import NearlineUpdater  # noqa: F401
from photon_ml_tpu.serving.registry import (  # noqa: F401
    ModelRegistry,
    publish_version,
    scan_versions,
)
from photon_ml_tpu.serving.router import (  # noqa: F401
    FleetRouter,
    FleetUnavailable,
    FleetView,
    fleet_lookups_from_version_dir,
    scan_announce,
    write_announce,
)
from photon_ml_tpu.serving.server import (  # noqa: F401
    ScoringServer,
    ScoringService,
    serve_stdio,
)
from photon_ml_tpu.serving.shard import (  # noqa: F401
    ShardBudgetError,
    ShardMemberSource,
    load_member_engine,
    member_owned_ranges,
    slice_model_for_member,
)

__all__ = [
    "ScoringEngine",
    "BadRequest",
    "MicroBatcher",
    "ContinuousBatcher",
    "Overloaded",
    "Draining",
    "ModelRegistry",
    "NearlineUpdater",
    "publish_version",
    "scan_versions",
    "ScoringService",
    "ScoringServer",
    "AsyncScoringServer",
    "serve_stdio",
    "FleetRouter",
    "FleetUnavailable",
    "FleetView",
    "fleet_lookups_from_version_dir",
    "scan_announce",
    "write_announce",
    "ShardBudgetError",
    "ShardMemberSource",
    "load_member_engine",
    "member_owned_ranges",
    "slice_model_for_member",
]
