"""photon-ml-tpu: a TPU-native (JAX/XLA/pjit/pallas) framework for training
Generalized Linear Models and GAME/GLMix mixed-effect models at scale.

Brand-new design with the capabilities of LinkedIn Photon-ML (reference
surveyed in SURVEY.md). The compute path is pure JAX: jit-compiled
``lax.while_loop`` optimizers (LBFGS/OWLQN/TRON), segment-sum sparse GLM
objectives, ``psum`` data-parallel reductions over a device mesh, and
``vmap``-batched per-entity random-effect solvers.
"""

__version__ = "0.1.0"

from photon_ml_tpu.ops.losses import (  # noqa: F401
    LOSSES,
    LogisticLoss,
    PointwiseLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    get_loss,
)
from photon_ml_tpu.ops.sparse import SparseBatch  # noqa: F401
from photon_ml_tpu.ops.objective import GLMObjective  # noqa: F401
from photon_ml_tpu.training import (  # noqa: F401
    SweepEntry,
    select_best_model,
    train_glm,
)
