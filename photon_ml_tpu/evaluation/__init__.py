from photon_ml_tpu.evaluation.evaluators import (  # noqa: F401
    EVALUATORS,
    auc,
    better_than,
    logistic_loss,
    parse_evaluator,
    poisson_loss,
    rmse,
    sharded_auc,
    sharded_precision_at_k,
    smoothed_hinge_loss,
    squared_loss,
)
