"""Evaluators: weighted metrics over (scores, labels, weights) arrays, plus
sharded (per-query/group) evaluators.

Reference analog: photon-api evaluation/ (SURVEY.md §2.c "Evaluators"):
AreaUnderROCCurveEvaluator (weighted rank AUC via sort-and-sweep,
AreaUnderROCCurveLocalEvaluator.scala:31-70), RMSE, logistic/squared/poisson/
smoothed-hinge losses, and ShardedEvaluator grouping by an id column with a
per-group local metric averaged (ShardedEvaluator.scala:19-37,
ShardedPrecisionAtKEvaluator). All metrics are jit-compatible device code;
groups are segment-sums over a group-id array.

``better_than`` direction per metric mirrors Evaluator.betterThan.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.losses import get_loss

Array = jax.Array

# metrics where larger is better
_MAXIMIZE = {"auc", "precision@k", "sharded_auc"}


def better_than(metric: str, a: float, b: float) -> bool:
    base = metric.split(":")[0]
    if base.startswith("precision@"):
        base = "precision@k"
    return a > b if base in _MAXIMIZE else a < b


# ---------------------------------------------------------------------------
# core metrics
# ---------------------------------------------------------------------------

def rmse(scores: Array, labels: Array, weights: Array) -> Array:
    se = weights * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(weights), 1e-12))


def _mean_loss(loss_name: str):
    loss = get_loss(loss_name)

    def f(scores: Array, labels: Array, weights: Array) -> Array:
        l = loss.loss(scores, labels)
        return jnp.sum(weights * l) / jnp.maximum(jnp.sum(weights), 1e-12)

    return f


logistic_loss = _mean_loss("logistic")
squared_loss = _mean_loss("squared")
poisson_loss = _mean_loss("poisson")
smoothed_hinge_loss = _mean_loss("smoothed_hinge")


def auc(scores: Array, labels: Array, weights: Array) -> Array:
    """Weighted ROC AUC by a single sort-and-sweep (rank statistic):

        AUC = [ sum_pos w_i * R_i - W_pos*(W_pos+... ) ] / (W_pos * W_neg)

    where R_i is the weighted mid-rank. Ties in score get average rank,
    matching the reference's tied-score handling
    (AreaUnderROCCurveLocalEvaluator.scala:31-70). Zero-weight (padding)
    rows are inert. Returns 0.5 when one class is absent.
    """
    pos = (labels > 0.5).astype(scores.dtype) * weights
    neg = (labels <= 0.5).astype(scores.dtype) * weights

    order = jnp.argsort(scores)  # ascending
    s = scores[order]
    p = pos[order]
    n = neg[order]
    w = p + n

    # weighted rank: cumulative weight up to-and-including, averaged with the
    # exclusive prefix -> mid-rank for the element itself
    cum = jnp.cumsum(w)
    rank = cum - 0.5 * w  # mid-rank of each element in weight space

    # tie groups: average the mid-rank over equal scores.
    # segment ids for equal-score runs:
    new_group = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num_seg = s.shape[0]
    g_w = jax.ops.segment_sum(w, gid, num_segments=num_seg, indices_are_sorted=True)
    g_rw = jax.ops.segment_sum(
        rank * w, gid, num_segments=num_seg, indices_are_sorted=True
    )
    g_mid = g_rw / jnp.maximum(g_w, 1e-30)  # weighted average rank per tie group
    rank_tied = g_mid[gid]

    w_pos = jnp.sum(p)
    w_neg = jnp.sum(n)
    sum_pos_rank = jnp.sum(rank_tied * p)
    # U statistic: sum of positive ranks minus the ranks positives occupy
    # among themselves (w_pos^2/2), over the pos*neg pair mass
    u = sum_pos_rank - 0.5 * w_pos * w_pos
    denom = w_pos * w_neg
    return jnp.where(denom > 0, u / jnp.maximum(denom, 1e-30), 0.5)


EVALUATORS: dict[str, Callable[[Array, Array, Array], Array]] = {
    "auc": auc,
    "rmse": rmse,
    "logistic_loss": logistic_loss,
    "squared_loss": squared_loss,
    "poisson_loss": poisson_loss,
    "smoothed_hinge_loss": smoothed_hinge_loss,
}


# ---------------------------------------------------------------------------
# sharded (per-group) evaluators
# ---------------------------------------------------------------------------

def sharded_auc(
    scores: Array, labels: Array, weights: Array, group_ids: Array, num_groups: int
) -> Array:
    """Mean per-group WEIGHTED AUC over groups that have both classes.

    The reference groups scores by an id column and averages a weight-aware
    local AUC per group (ShardedAreaUnderROCCurveEvaluator delegating to
    AreaUnderROCCurveLocalEvaluator.scala:31-70). Same weighted mid-rank
    statistic as the global ``auc`` above, computed group-relative in one
    lexsort + sweep, fully on device. Zero-weight (padding) rows are inert.
    """
    order = jnp.lexsort((scores, group_ids))
    g = group_ids[order]
    s = scores[order]
    w = weights[order]
    pos = (labels[order] > 0.5).astype(scores.dtype) * w
    neg = (labels[order] <= 0.5).astype(scores.dtype) * w
    wv = pos + neg

    # group-relative weighted mid-rank: cumulative weight within the group,
    # averaged with the exclusive prefix
    cum = jnp.cumsum(wv)
    g_start = jax.ops.segment_min(
        cum - wv, g, num_segments=num_groups, indices_are_sorted=True
    )
    g_start = jnp.where(jnp.isfinite(g_start), g_start, 0.0)  # empty groups
    rank = cum - 0.5 * wv - g_start[g]

    # ties: weighted-average the mid-rank over equal (group, score) runs
    new_run = jnp.concatenate(
        [jnp.ones((1,), bool), (s[1:] != s[:-1]) | (g[1:] != g[:-1])]
    )
    rid = jnp.cumsum(new_run.astype(jnp.int32)) - 1
    n_runs = scores.shape[0]
    r_w = jax.ops.segment_sum(wv, rid, num_segments=n_runs, indices_are_sorted=True)
    r_rw = jax.ops.segment_sum(
        rank * wv, rid, num_segments=n_runs, indices_are_sorted=True
    )
    r_mid = r_rw / jnp.maximum(r_w, 1e-30)
    rank_tied = r_mid[rid]

    w_pos = jax.ops.segment_sum(pos, g, num_segments=num_groups,
                                indices_are_sorted=True)
    w_neg = jax.ops.segment_sum(neg, g, num_segments=num_groups,
                                indices_are_sorted=True)
    sum_pos_rank = jax.ops.segment_sum(
        rank_tied * pos, g, num_segments=num_groups, indices_are_sorted=True
    )
    # U statistic per group (see auc above)
    u = sum_pos_rank - 0.5 * w_pos * w_pos
    pairs = w_pos * w_neg
    has_both = pairs > 0
    per_group = jnp.where(has_both, u / jnp.maximum(pairs, 1e-30), 0.0)
    n_scored = jnp.sum(has_both.astype(scores.dtype))
    return jnp.sum(per_group) / jnp.maximum(n_scored, 1.0)


def sharded_precision_at_k(
    scores: Array,
    labels: Array,
    weights: Array,
    group_ids: Array,
    num_groups: int,
    k: int,
) -> Array:
    """Mean per-group precision@k (PrecisionAtKLocalEvaluator analog):
    fraction of the top-k scored valid items per group that are positive."""
    valid = weights > 0
    # rank within group by descending score: lexsort by (group, -score)
    order = jnp.lexsort((-scores, group_ids))
    g = group_ids[order]
    y = ((labels[order] > 0.5) & valid[order]).astype(scores.dtype)
    v = valid[order].astype(scores.dtype)

    cum_v = jnp.cumsum(v)
    start = jax.ops.segment_min(
        cum_v - v, g, num_segments=num_groups, indices_are_sorted=True
    )
    rank_in_group = cum_v - v - start[g]  # 0-based among valid rows
    in_top_k = (rank_in_group < k) & (v > 0)

    hits = jax.ops.segment_sum(
        jnp.where(in_top_k, y, 0.0), g, num_segments=num_groups,
        indices_are_sorted=True,
    )
    counts = jax.ops.segment_sum(
        in_top_k.astype(scores.dtype), g, num_segments=num_groups,
        indices_are_sorted=True,
    )
    has_any = counts > 0
    per_group = jnp.where(has_any, hits / jnp.maximum(counts, 1.0), 0.0)
    n_groups_scored = jnp.sum(has_any.astype(scores.dtype))
    return jnp.sum(per_group) / jnp.maximum(n_groups_scored, 1.0)


def parse_evaluator(spec: str):
    """Parse evaluator spec strings like 'auc', 'rmse', 'precision@5:queryId',
    'auc:queryId' (sharded variants carry the grouping column after ':'),
    mirroring EvaluatorType/ShardedEvaluatorType parsing."""
    spec = spec.strip().lower()
    if ":" in spec:
        metric, group_col = spec.split(":", 1)
        if metric.startswith("precision@"):
            k = int(metric.split("@")[1])
            return ("sharded_precision_at_k", group_col, k)
        if metric == "auc":
            return ("sharded_auc", group_col, None)
        raise ValueError(f"unknown sharded evaluator '{spec}'")
    if spec not in EVALUATORS:
        raise ValueError(f"unknown evaluator '{spec}'. Known: {sorted(EVALUATORS)}")
    return (spec, None, None)
