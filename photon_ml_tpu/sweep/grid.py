"""Typed sweep-grid specs: the ``--sweep lambda=...`` grammar.

Reference analog: photon-client GameParams' per-coordinate
``regularization-weights`` lists (GameParams.scala:318-334) — the
GameEstimator trains one CoordinateDescent run per weight combination.
Here the grid is a first-class typed object with a compact string grammar:

    lambda=1e-4:1e2:log16       16 log-spaced points in [1e-4, 1e2]
    lambda=0.5:2.5:lin5         5 linearly spaced points
    lambda=0.01,0.1,1,10        explicit list
    lambda.fixed=0.1,1          per-coordinate override for GLMix
                                (coordinate name after the dot)

Points are deduplicated and ordered DESCENDING deterministically — the
warm-started regularization path trains most-regularized first
(ModelTraining.scala:166 ``sortWith(_ >= _)``), and the sweep runner's
config axis g is exactly this order (lane g-1 is the more regularized
neighbor lane g warm-starts from).

Per-coordinate overrides do NOT form a cartesian product: every
coordinate's grid must have the same length G (or length 1, broadcast),
because the config axis is ONE shared vmap lane — lane g uses
``lambda.fixed[g]`` for the FE block and ``lambda.perUser[g]`` for the RE
block. Cartesian sweeps remain ``GameEstimator.fit_grid``'s job.

Malformed specs raise :class:`SweepSpecError` naming the offending token —
a typo must never silently train the default grid.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

__all__ = ["SweepSpecError", "SweepGrid", "parse_sweep_spec", "parse_range"]


class SweepSpecError(ValueError):
    """A sweep spec failed to parse; the message names the offending token."""

    def __init__(self, token: str, message: str):
        super().__init__(f"bad sweep spec {token!r}: {message}")
        self.token = token


def _parse_float(token: str, context: str) -> float:
    try:
        value = float(token)
    except ValueError:
        raise SweepSpecError(context, f"{token!r} is not a number") from None
    if not np.isfinite(value):
        raise SweepSpecError(context, f"{token!r} is not finite")
    if value < 0:
        raise SweepSpecError(
            context, f"negative regularization weight {token!r}"
        )
    return value


def parse_range(text: str, context: Optional[str] = None) -> tuple[float, ...]:
    """One grid value: ``lo:hi:logN`` / ``lo:hi:linN`` / ``a,b,c`` —
    returns the DESCENDING deduplicated point tuple."""
    context = context if context is not None else text
    text = text.strip()
    if not text:
        raise SweepSpecError(context, "empty grid (no points)")
    if ":" in text:
        parts = text.split(":")
        if len(parts) != 3:
            raise SweepSpecError(
                context, "ranges are 'lo:hi:logN' or 'lo:hi:linN'"
            )
        lo = _parse_float(parts[0], context)
        hi = _parse_float(parts[1], context)
        kind = parts[2].strip().lower()
        if kind.startswith("log"):
            scale, count_text = "log", kind[3:]
        elif kind.startswith("lin"):
            scale, count_text = "lin", kind[3:]
        else:
            raise SweepSpecError(
                context,
                f"spacing {parts[2]!r} must be 'logN' or 'linN'",
            )
        try:
            count = int(count_text)
        except ValueError:
            raise SweepSpecError(
                context, f"point count {count_text!r} is not an integer"
            ) from None
        if count <= 0:
            raise SweepSpecError(context, f"zero/negative point count {count}")
        if lo > hi:
            raise SweepSpecError(
                context, f"inverted range (lo {lo:g} > hi {hi:g})"
            )
        if count == 1:
            points = np.asarray([hi])
        elif scale == "log":
            if lo <= 0:
                raise SweepSpecError(
                    context, f"log spacing needs lo > 0, got {lo:g}"
                )
            points = np.logspace(np.log10(lo), np.log10(hi), count)
        else:
            points = np.linspace(lo, hi, count)
    else:
        points = np.asarray(
            [_parse_float(p, context) for p in text.split(",") if p.strip()]
        )
        if points.size == 0:
            raise SweepSpecError(context, "empty grid (no points)")
    # deterministic descending path order, exact duplicates removed
    points = np.unique(points.astype(np.float64))[::-1]
    return tuple(float(v) for v in points)


@dataclasses.dataclass(frozen=True)
class SweepGrid:
    """A parsed sweep: the default λ grid plus per-coordinate overrides.

    ``default`` and every override are DESCENDING tuples. ``size`` is the
    shared config-axis length G; overrides of length 1 broadcast to G.
    """

    default: Optional[tuple[float, ...]] = None
    per_coordinate: Mapping[str, tuple[float, ...]] = dataclasses.field(
        default_factory=dict
    )

    def __post_init__(self):
        lengths = {
            len(v) for v in self.per_coordinate.values() if len(v) > 1
        }
        if self.default is not None and len(self.default) > 1:
            lengths.add(len(self.default))
        if len(lengths) > 1:
            raise SweepSpecError(
                "grid",
                "per-coordinate grids must share one config-axis length "
                f"(or be length 1); got lengths {sorted(lengths)} — the "
                "sweep axis is one shared vmap lane, not a cartesian "
                "product (use GameEstimator.fit_grid for products)",
            )
        if self.default is None and not self.per_coordinate:
            raise SweepSpecError("grid", "no lambda grid given")

    @property
    def size(self) -> int:
        sizes = [len(v) for v in self.per_coordinate.values()]
        if self.default is not None:
            sizes.append(len(self.default))
        return max(sizes)

    def for_coordinate(self, name: str) -> tuple[float, ...]:
        """Coordinate ``name``'s λ per config lane (length ``size``)."""
        points = self.per_coordinate.get(name, self.default)
        if points is None:
            raise SweepSpecError(
                f"lambda.{name}",
                "coordinate has no grid and no default `lambda=` was given",
            )
        if len(points) == 1 and self.size > 1:
            points = points * self.size
        return points

    def to_json(self) -> dict:
        out: dict = {}
        if self.default is not None:
            out["lambda"] = list(self.default)
        for name, points in self.per_coordinate.items():
            out[f"lambda.{name}"] = list(points)
        return out


def parse_sweep_spec(specs: str | Sequence[str]) -> SweepGrid:
    """Parse one or more ``lambda[.coordinate]=<grid>`` tokens.

    ``specs`` may be a single string (tokens separated by whitespace
    and/or ``;``) or a sequence of tokens (one per ``--sweep`` flag).
    """
    if isinstance(specs, str):
        tokens = [t for t in specs.replace(";", " ").split() if t]
    else:
        tokens = [t for raw in specs for t in str(raw).replace(";", " ").split()]
    if not tokens:
        raise SweepSpecError("<empty>", "no sweep tokens given")
    default: Optional[tuple[float, ...]] = None
    per_coordinate: dict[str, tuple[float, ...]] = {}
    for token in tokens:
        key, eq, value = token.partition("=")
        key = key.strip()
        if not eq:
            raise SweepSpecError(token, "expected 'lambda[.coordinate]=grid'")
        if not value.strip():
            raise SweepSpecError(token, "empty grid (no points)")
        if key == "lambda":
            if default is not None:
                raise SweepSpecError(token, "duplicate 'lambda=' token")
            default = parse_range(value, context=token)
        elif key.startswith("lambda.") and len(key) > len("lambda."):
            coord = key[len("lambda."):]
            if coord in per_coordinate:
                raise SweepSpecError(token, f"duplicate grid for '{coord}'")
            per_coordinate[coord] = parse_range(value, context=token)
        else:
            raise SweepSpecError(
                token, f"unknown key {key!r} (expected 'lambda' or "
                "'lambda.<coordinate>')"
            )
    return SweepGrid(default=default, per_coordinate=per_coordinate)
