"""Best-model selection over a finished sweep + export to the serving
registry.

Reference analog: photon-client ModelSelection (AUC for classifiers, RMSE
for linear regression, Poisson loss for Poisson) and the GameEstimator's
evaluator-ranked (config, model, evaluation) output. Here every config
lane is scored ON DEVICE in one vmapped evaluator call — a [G, n]
score matrix in, a [G] metric vector out, ONE host fetch for the whole
sweep — then a host-side selection policy picks the winner and
:func:`export_winner` publishes it through ``serving.registry
.publish_version`` in the exact layout a live ``ModelRegistry``
hot-swaps from.

Degenerate-metric discipline (the silent-argmax-over-NaNs hazard): lanes
whose metric is NaN (all-NaN validation columns, empty effective splits)
are EXCLUDED from selection with a warning + ``sweep.nan_configs``
counter; if every lane is NaN, selection raises a typed
:class:`SweepSelectionError` instead of exporting garbage. Single-class
AUC degrades to the evaluators' documented 0.5 fallback and stays
selectable.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import lru_cache
from typing import Optional

import jax
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.evaluation.evaluators import EVALUATORS, better_than
from photon_ml_tpu.game.coordinate_descent import padded_validation_arrays
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.telemetry.xla import instrumented_jit

logger = logging.getLogger("photon_ml_tpu.sweep")

__all__ = [
    "SweepSelectionError",
    "SweepSelection",
    "default_metric",
    "evaluate_sweep",
    "select_best",
    "run_selection",
    "export_winner",
]


class SweepSelectionError(ValueError):
    """No config lane produced a usable validation metric (or the metric
    spec itself is unusable for sweeps); the message names the metric and
    the lane count so the failure is diagnosable from the log alone."""


@dataclasses.dataclass
class SweepSelection:
    """The outcome of scoring + selecting over G config lanes."""

    index: int  # winning lane (lanes ordered by descending λ)
    metric: str
    metrics: np.ndarray  # f64[G]; NaN = lane excluded
    policy: str

    @property
    def best_value(self) -> float:
        return float(self.metrics[self.index])

    def to_json(self) -> dict:
        return {
            "index": self.index,
            "metric": self.metric,
            "policy": self.policy,
            "best_value": self.best_value,
            "values": [
                None if np.isnan(v) else float(v) for v in self.metrics
            ],
        }


def default_metric(task: str) -> str:
    """ModelSelection.scala parity: AUC for binary classifiers, RMSE for
    linear regression, data log-likelihood (poisson loss) for Poisson."""
    from photon_ml_tpu.ops.losses import get_loss

    task = get_loss(task).name
    if task in ("logistic", "smoothed_hinge"):
        return "auc"
    if task == "squared":
        return "rmse"
    return "poisson_loss"


@lru_cache(maxsize=16)
def _sweep_evaluator(metric: str):
    fn = EVALUATORS[metric]

    def run(scores, labels, weights):
        return jax.vmap(fn, in_axes=(0, None, None))(scores, labels, weights)

    return instrumented_jit(run, name=f"sweep_eval_{metric}", multi_shape=True)


def evaluate_sweep(
    result, validation_data: GameDataset, metric: Optional[str] = None
) -> tuple[str, np.ndarray]:
    """Score EVERY config lane against the validation split on device.

    ``result`` is a :class:`~photon_ml_tpu.sweep.runner.GameSweepResult`.
    Returns ``(metric_name, values[G])`` — the [G, n] score matrix, the
    vmapped evaluator, and the single host fetch are the whole round
    trip. Sharded (grouped) evaluator specs are not vmappable over the
    config axis and raise :class:`SweepSelectionError` naming the spec.
    """
    metric = metric or default_metric(result.task)
    if metric not in EVALUATORS:
        raise SweepSelectionError(
            f"metric '{metric}' is not sweep-scorable (sharded/grouped "
            f"evaluators need per-group state); pick one of "
            f"{sorted(EVALUATORS)}"
        )
    scores = result.validation_scores(validation_data)  # [G, n_pad]
    labels, weights, offsets = padded_validation_arrays(
        validation_data, scores.shape[1]
    )
    values = _sweep_evaluator(metric)(
        scores + offsets[None, :], labels, weights
    )
    fetched = np.asarray(
        telemetry.sync_fetch(values, label=f"sweep_eval:{metric}"),
        dtype=np.float64,
    )
    return metric, fetched


def select_best(
    metrics: np.ndarray,
    metric_name: str,
    policy: str = "best",
    rel_tol: float = 0.01,
) -> int:
    """Pick the winning lane index from per-lane metric values.

    Policies (lanes are ordered by DESCENDING λ, so lower index = more
    regularized):

    - ``"best"``: the best metric value; ties break toward the lower
      index (the more regularized, simpler model).
    - ``"parsimonious"``: the LOWEST-index lane within ``rel_tol``
      (relative) of the best value — the one-stderr-rule analog that
      prefers stronger regularization when the metric is flat.

    NaN lanes are excluded (``sweep.nan_configs`` counter + warning);
    all-NaN raises :class:`SweepSelectionError`.
    """
    metrics = np.asarray(metrics, np.float64)
    valid = np.isfinite(metrics)
    n_bad = int(np.sum(~valid))
    if n_bad:
        telemetry.counter("sweep.nan_configs").inc(n_bad)
        logger.warning(
            "sweep: %d of %d configs produced non-finite '%s' metrics; "
            "excluded from selection",
            n_bad, len(metrics), metric_name,
        )
    if not valid.any():
        raise SweepSelectionError(
            f"all {len(metrics)} sweep configs produced non-finite "
            f"'{metric_name}' validation metrics — nothing to select "
            "(check the validation split for empty/NaN columns)"
        )
    maximize = better_than(metric_name, 1.0, 0.0)
    masked = np.where(valid, metrics, -np.inf if maximize else np.inf)
    best_value = masked.max() if maximize else masked.min()
    if policy == "best":
        # np.argmax/argmin return the FIRST best index = most regularized
        return int(masked.argmax() if maximize else masked.argmin())
    if policy == "parsimonious":
        span = abs(best_value) * rel_tol
        ok = valid & (
            (metrics >= best_value - span)
            if maximize
            else (metrics <= best_value + span)
        )
        return int(np.nonzero(ok)[0][0])
    raise SweepSelectionError(
        f"unknown selection policy '{policy}' (best|parsimonious)"
    )


def run_selection(
    result,
    validation_data: GameDataset,
    metric: Optional[str] = None,
    policy: str = "best",
    rel_tol: float = 0.01,
) -> SweepSelection:
    """evaluate_sweep + select_best + per-config telemetry spans."""
    metric_name, values = evaluate_sweep(result, validation_data, metric)
    index = select_best(values, metric_name, policy=policy, rel_tol=rel_tol)
    telemetry.gauge("sweep.selected_index").set(index)
    telemetry.gauge("sweep.selected_metric").set(float(values[index]))
    result.emit_config_spans(metrics=values, metric_name=metric_name)
    return SweepSelection(
        index=index, metric=metric_name, metrics=values, policy=policy
    )


def export_winner(
    model,
    index_maps,
    registry_dir: str,
    selection: Optional[SweepSelection] = None,
    extra_metadata: Optional[dict] = None,
) -> str:
    """Publish the winning model as the next registry version — the exact
    ``publish_version`` layout ``serving/registry.py`` hot-swaps from
    (feature indexes first, metadata last, atomic rename). Returns the
    published version path."""
    from photon_ml_tpu.serving.registry import publish_version

    meta = dict(extra_metadata or {})
    if selection is not None:
        meta["sweep_selection"] = selection.to_json()
    path = publish_version(registry_dir, model, index_maps,
                           extra_metadata=meta)
    telemetry.counter("sweep.published_versions").inc()
    return path
