"""Vmapped multi-λ training: G regularization configs in ONE executable.

Reference analog: photon-api GameEstimator trains one CoordinateDescent
run PER regularization weight and picks the best by evaluator
(GameEstimator.scala:279-398). Because this repo's solvers are jitted
``lax.while_loop``s, a λ-grid is just one more ``vmap`` axis: the G
configs of the fixed-effect solve (and of every per-entity random-effect
bucket solve, where the config axis composes with the existing entity
vmap lane) batch into a single ``instrumented_jit`` executable — G small
dense problems is exactly the shape the MXU wants.

Warm-started regularization path: λs are ordered DESCENDING (grid.py), so
lane g-1 is lane g's more-regularized neighbor. Each round/CD iteration
initializes config g from config g-1's solution — but ONLY into lanes
that did not converge last round; converged lanes keep their own optimum,
enter the masked while-loop already-converged, and stop contributing
iterations (the per-config convergence mask the vmapped ``while_loop``
batching rule provides for free).

All solvers register with ``multi_shape=True``: the G-config warmup
compiles a by-design signature set and must never trip the
recompile-storm gate (``xla.recompiles`` stays flat across a warmed
sweep).

Telemetry: ``sweep.solves`` / ``sweep.nan_configs`` counters,
``sweep.configs_total`` / ``sweep.configs_done`` gauges (surfaced on the
30 s heartbeat line), a ``sweep > sweep_iteration > coordinate:<name>``
span tree, and one ``sweep_config`` span per lane at the end carrying the
per-config convergence summary the run report renders as a table.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectBucketModel,
    RandomEffectModel,
    map_vocab_codes,
)
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import (
    CONVERGENCE_REASON_NAMES,
    FUNCTION_VALUES_CONVERGED,
    MAX_ITERATIONS,
    NOT_CONVERGED,
)
from photon_ml_tpu.optim.factory import (
    OptimizerConfig,
    dispatch_solve,
    split_reg_weights,
)
from photon_ml_tpu.sweep.grid import SweepGrid
from photon_ml_tpu.telemetry.xla import instrumented_jit

Array = jax.Array

__all__ = [
    "GlmSweepResult",
    "GameSweepResult",
    "SweepUnsupportedError",
    "path_warm_start",
    "re_bootstrap_solver",
    "sweep_glm",
    "sweep_game",
]


class SweepUnsupportedError(ValueError):
    """A training feature the vmapped sweep path does not batch yet; the
    message names the coordinate and the single-fit alternative."""


# ---------------------------------------------------------------------------
# batched solvers (one instrumented_jit each; multi_shape by design)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=32)
def _fe_sweep_solver(config: OptimizerConfig, with_residual: bool):
    """G-config GLM solve: objective l2 leaf, OWLQN l1 and (optionally)
    per-config residual offsets map over the config axis; the design
    broadcasts, so data movement is shared across lanes."""
    if with_residual:
        def run(obj, batch, res_off, w0, l2s, l1s, constraints):
            def one(res_g, w0_g, l2_g, l1_g):
                b = batch.with_offsets(batch.offsets + res_g)
                return dispatch_solve(
                    glm_adapter(obj.with_l2(l2_g), b), w0_g, config, l1_g,
                    constraints,
                )

            return jax.vmap(one)(res_off, w0, l2s, l1s)
    else:
        def run(obj, batch, w0, l2s, l1s, constraints):
            def one(w0_g, l2_g, l1_g):
                return dispatch_solve(
                    glm_adapter(obj.with_l2(l2_g), batch), w0_g, config,
                    l1_g, constraints,
                )

            return jax.vmap(one)(w0, l2s, l1s)

    return instrumented_jit(run, name="sweep_fe_solve", multi_shape=True)


@lru_cache(maxsize=32)
def _re_sweep_solver(config: OptimizerConfig):
    """G-config x E-entity bucket solve: the config axis composes as an
    OUTER vmap over the existing per-entity vmap lane — one executable
    solves G*E independent small problems with the bucket design
    broadcast across configs."""

    def run(obj, ebatch, extra_off, w0, l2s, l1s):
        def one_cfg(extra_g, w0_g, l2_g, l1_g):
            obj_g = obj.with_l2(l2_g)
            eb = dataclasses.replace(
                ebatch, offsets=ebatch.offsets + extra_g
            )

            def one_entity(eb_e, w0_e):
                return dispatch_solve(
                    glm_adapter(obj_g, eb_e), w0_e, config, l1_g
                )

            return jax.vmap(one_entity)(eb, w0_g)

        return jax.vmap(one_cfg)(extra_off, w0, l2s, l1s)

    return instrumented_jit(run, name="sweep_re_solve", multi_shape=True)


@lru_cache(maxsize=32)
def re_bootstrap_solver(config: OptimizerConfig):
    """B-resample x E-entity bucket solve for the GLMix bootstrap
    (diagnostics.bootstrap): identical lane composition to
    :func:`_re_sweep_solver`, but the outer vmap axis carries B
    multinomial weight resamples instead of G regularization configs —
    ``lane_weights`` [B, E, R] scales the bucket's base row weights per
    lane, ``w0`` [E, K] (the point estimate) broadcasts across B so
    every lane warm-starts from the fitted coefficients. One executable
    solves B*E independent small problems with the bucket design
    broadcast across resamples, which is why B=64 costs well under 2x a
    single fit (bench_diagnostics)."""

    def run(obj, ebatch, lane_weights, w0, l1):
        def one_sample(wts_b):
            eb = dataclasses.replace(
                ebatch, weights=ebatch.weights * wts_b
            )

            def one_entity(eb_e, w0_e):
                return dispatch_solve(
                    glm_adapter(obj, eb_e), w0_e, config, l1
                )

            return jax.vmap(one_entity)(eb, w0)

        return jax.vmap(one_sample)(lane_weights)

    return instrumented_jit(run, name="bootstrap_re_solve", multi_shape=True)


@lru_cache(maxsize=8)
def _fe_sweep_scorer():
    def run(batch, w):
        return jax.vmap(batch.dot_rows)(w)

    return instrumented_jit(run, name="sweep_fe_score", multi_shape=True)


@lru_cache(maxsize=8)
def _re_sweep_scorer():
    def run(scores, coeffs, ebatch, row_index):
        # coeffs [G, E, K] -> margins [G, E, R] -> scatter into [G, n_pad]
        def one_cfg(c):
            return jax.vmap(lambda w, b: b.dot_rows(w))(c, ebatch)

        margins = jax.vmap(one_cfg)(coeffs)
        idx = row_index.reshape(-1)
        vals = margins.reshape(margins.shape[0], -1)
        vals = jnp.where(idx[None, :] >= 0, vals, 0.0)
        return scores.at[:, jnp.maximum(idx, 0)].add(vals)

    return instrumented_jit(run, name="sweep_re_score", multi_shape=True)


@lru_cache(maxsize=8)
def _re_residual_gather():
    def run(residual, row_index):
        # residual [G, n_pad] -> bucket layout [G, E, R] (row_index gather;
        # padded rows contribute 0 — the addScoresToOffsets analog)
        def one(res_g):
            return jnp.where(
                row_index >= 0,
                jnp.take(res_g, jnp.maximum(row_index, 0)),
                0.0,
            )

        return jax.vmap(one)(residual)

    return instrumented_jit(run, name="sweep_re_residual", multi_shape=True)


@lru_cache(maxsize=8)
def _re_val_scorer():
    """Validation scoring of ALL G coefficient tables at once: the
    (bucket, pos, local-feature) lookup per nnz is config-independent and
    computed once; only the final coefficient gather carries the G axis —
    no per-config host round trips."""

    def run(scores, coeffs, projection, vals, rows, pos, gcols):
        proj_rows = projection[pos]  # [m, K] (config-independent)
        K = projection.shape[1]
        k = jnp.minimum(jax.vmap(jnp.searchsorted)(proj_rows, gcols), K - 1)
        hit = (
            jnp.take_along_axis(proj_rows, k[:, None], axis=1)[:, 0] == gcols
        )
        w = jnp.where(hit[None, :], coeffs[:, pos, k], 0.0)  # [G, m]
        return scores.at[:, rows].add(vals[None, :] * w)

    return instrumented_jit(run, name="sweep_re_val_score", multi_shape=True)


# ---------------------------------------------------------------------------
# warm-started path
# ---------------------------------------------------------------------------


def path_warm_start(w: Array, reasons: Array) -> Array:
    """Next-round inits along the regularization path: lane g takes lane
    g-1's solution (its more-regularized neighbor, λs descending) — but
    ONLY where lane g did not converge (``reasons`` says MaxIterations /
    still running); converged lanes keep their own optimum and freeze in
    the masked while-loop after the convergence check."""
    shifted = jnp.concatenate([w[:1], w[:-1]], axis=0)
    unconverged = (reasons == MAX_ITERATIONS) | (reasons == NOT_CONVERGED)
    keep = ~unconverged
    return jnp.where(keep.reshape((-1,) + (1,) * (w.ndim - 1)), w, shifted)


def _lane_unconverged(reasons: Array) -> Array:
    """Per-lane unconverged mask from a [G] or [G, E] reason array."""
    un = (reasons == MAX_ITERATIONS) | (reasons == NOT_CONVERGED)
    return un if un.ndim == 1 else jnp.any(un, axis=tuple(range(1, un.ndim)))


# ---------------------------------------------------------------------------
# plain-GLM sweep (the headline-config path; any batch layout)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GlmSweepResult:
    """One finished multi-λ GLM sweep (config axis = descending λ)."""

    lambdas: tuple[float, ...]
    w: Array  # [G, d]
    values: Array  # [G] final objective values
    iterations: np.ndarray  # i32[G]
    reasons: np.ndarray  # i32[G]
    data_passes: np.ndarray  # i32[G]
    rounds: int

    @property
    def size(self) -> int:
        return len(self.lambdas)

    def reason_names(self) -> list[str]:
        return [
            CONVERGENCE_REASON_NAMES.get(int(r), str(int(r)))
            for r in self.reasons
        ]


def sweep_glm(
    batch,
    task: str,
    lambdas: Sequence[float],
    config: OptimizerConfig,
    *,
    warm_start: bool = True,
    rounds: Optional[int] = None,
    w_start: Optional[Array] = None,
    constraints=None,
    mesh=None,
) -> GlmSweepResult:
    """Train one GLM per λ, all in one vmapped executable.

    ``rounds`` (default 2 with ``warm_start``, else 1) is the number of
    batched solve passes: round 0 is cold (every lane from ``w_start``),
    later rounds re-init unconverged lanes from their more-regularized
    neighbor (:func:`path_warm_start`). ``config.regularization_weight``
    is ignored — the grid is the sweep axis. With ``mesh`` (a mesh with a
    model or batch axis) the config axis is sharded across devices:
    lanes partition, the design replicates.
    """
    if not lambdas:
        raise ValueError("sweep_glm needs a non-empty lambda grid")
    config.validate(task)
    lams = tuple(sorted((float(v) for v in lambdas), reverse=True))
    G = len(lams)
    if rounds is None:
        rounds = 2 if (warm_start and G > 1) else 1
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    n_feat = int(batch.num_features)
    if w_start is None:
        w_start = jnp.zeros((n_feat,), jnp.float32)
    if constraints is None:
        constraints = config.build_box_constraints(n_feat)
    key_cfg = dataclasses.replace(config, regularization_weight=0.0)
    solver = _fe_sweep_solver(key_cfg, with_residual=False)
    obj = make_objective(task)

    l2s, l1s = split_reg_weights(config.regularization, lams)
    W = jnp.broadcast_to(w_start, (G, n_feat))
    pad = 0
    if mesh is not None:
        from photon_ml_tpu.parallel import sharding as psharding
        from photon_ml_tpu.telemetry.xla import record_collective

        axis = psharding.model_axis(mesh) or psharding.data_axis(mesh)
        if axis is not None:
            n_dev = psharding.axis_size(mesh, axis)
            pad = (-G) % n_dev
            if pad:
                # duplicate the smallest λ into the pad lanes; sliced off
                lams_p = lams + (lams[-1],) * pad
                l2s, l1s = split_reg_weights(config.regularization, lams_p)
                W = jnp.broadcast_to(w_start, (G + pad, n_feat))
            eshard = psharding.entity_sharding(mesh, axis)
            W = jax.device_put(W, eshard)
            l2s = jax.device_put(l2s, eshard)
            l1s = jax.device_put(l1s, eshard)
            batch = psharding.place_replicated(batch, mesh)
            if constraints is not None:
                constraints = psharding.place_replicated(constraints, mesh)
            # lanes are independent; per-iteration traffic is the masked
            # while-loop's one-scalar convergence all-reduce
            record_collective(
                "sweep_glm_solve", "psum", n_dev, 4,
                count=max(int(config.max_iterations), 1) * rounds,
            )

    telemetry.gauge("sweep.configs_total").set(G)
    telemetry.gauge("sweep.configs_done").set(0)
    res = None
    with telemetry.span("sweep", task=task, configs=G, rounds=rounds):
        for r in range(rounds):
            with telemetry.span("sweep_round", round=r):
                w0 = W if r == 0 else path_warm_start(W, res.reason)
                res = solver(obj, batch, w0, l2s, l1s, constraints)
                W = res.w
            telemetry.counter("sweep.solves").inc(G)
            telemetry.gauge("sweep.configs_done").set(
                int(round(G * (r + 1) / rounds))
            )
    packed = jnp.concatenate(
        [
            res.iterations.astype(jnp.float32),
            res.reason.astype(jnp.float32),
            jnp.broadcast_to(
                jnp.asarray(res.data_passes, jnp.float32), res.reason.shape
            ),
        ]
    )
    fetched = np.asarray(
        telemetry.sync_fetch(packed, label="sweep_glm")
    ).reshape(3, -1)
    result = GlmSweepResult(
        lambdas=lams,
        w=W[:G],
        values=res.value[:G],
        iterations=fetched[0, :G].astype(np.int32),
        reasons=fetched[1, :G].astype(np.int32),
        data_passes=fetched[2, :G].astype(np.int32),
        rounds=rounds,
    )
    _emit_config_spans(
        result.lambdas,
        {"lambda": result.lambdas},
        result.iterations,
        result.reasons,
        values=np.asarray(
            telemetry.sync_fetch(result.values, label="sweep_glm_values")
        ),
    )
    return result


def _emit_config_spans(
    lambdas: Sequence[float],
    lambda_by_key: Mapping[str, Sequence[float]],
    iterations: np.ndarray,
    reasons: np.ndarray,
    values: Optional[np.ndarray] = None,
    metrics: Optional[np.ndarray] = None,
    metric_name: Optional[str] = None,
) -> None:
    """One ``sweep_config`` span per lane: the per-config convergence
    record the run report renders as a table (round-trips through the
    trace JSONL)."""
    for g in range(len(lambdas)):
        attrs = {
            "index": g,
            "iterations": int(iterations[g]),
            "reason": CONVERGENCE_REASON_NAMES.get(
                int(reasons[g]), str(int(reasons[g]))
            ),
        }
        for key, lams in lambda_by_key.items():
            attrs[f"lambda.{key}" if key != "lambda" else "lambda"] = float(
                lams[g]
            )
        if values is not None:
            attrs["final_loss"] = float(values[g])
        if metrics is not None:
            attrs["metric"] = (
                None if np.isnan(metrics[g]) else float(metrics[g])
            )
            attrs["metric_name"] = metric_name
        with telemetry.span("sweep_config", **attrs):
            pass


# ---------------------------------------------------------------------------
# GAME sweep (FE + per-entity RE coordinates; shared config axis)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _FeState:
    name: str
    shard_name: str
    config: OptimizerConfig
    lambdas: tuple[float, ...]
    batch: object  # device SparseBatch with labels/offsets/weights
    l2s: Array
    l1s: Array
    constraints: object
    normalization: object
    solver: object
    W: Array  # [G, d] in SOLVE (normalized) space
    reasons: Optional[Array] = None
    iterations: Optional[Array] = None
    values: Optional[Array] = None

    def original_w(self) -> Array:
        if self.normalization is None:
            return self.W
        return jax.vmap(self.normalization.transform_model_coefficients)(
            self.W
        )


@dataclasses.dataclass
class _ReState:
    name: str
    config: OptimizerConfig
    lambdas: tuple[float, ...]
    red: object  # RandomEffectDataset
    ebatches: tuple  # per bucket: SparseBatch with leading entity axis
    l2s: Array
    l1s: Array
    solver: object
    tables: list  # per bucket [G, E, K]
    vocab: np.ndarray
    reasons: Optional[Array] = None  # [G] lane-aggregated
    iterations: Optional[Array] = None
    values: Optional[Array] = None


class GameSweepResult:
    """A finished multi-config GAME sweep: device coefficient tables per
    coordinate per lane, convergence summaries, and on-device scoring of
    every lane against a validation dataset."""

    def __init__(self, task, states, history, n_pad):
        self.task = task
        self._states = states  # name -> _FeState | _ReState
        self.history = history
        self._n_pad = n_pad
        self._convergence = None  # fetched once; the sweep is immutable

    @property
    def size(self) -> int:
        return len(next(iter(self._states.values())).lambdas)

    @property
    def coordinate_names(self) -> list[str]:
        return list(self._states)

    @property
    def lambdas(self) -> dict[str, tuple[float, ...]]:
        return {name: s.lambdas for name, s in self._states.items()}

    def convergence(self) -> dict[str, dict[str, np.ndarray]]:
        """Per-coordinate per-lane summary of the LAST update: iterations
        (RE: max over entities), reason codes (RE: worst over entities),
        final objective values (RE: summed over entities). Fetched from
        device ONCE and cached — callers (selection spans, the CLI
        summary) must not each pay the tunnel round trip."""
        if self._convergence is not None:
            return self._convergence
        out = {}
        for name, s in self._states.items():
            packed = jnp.stack(
                [
                    s.iterations.astype(jnp.float32),
                    s.reasons.astype(jnp.float32),
                    s.values.astype(jnp.float32),
                ]
            )
            fetched = np.asarray(
                telemetry.sync_fetch(packed, label=f"sweep:{name}")
            )
            out[name] = {
                "iterations": fetched[0].astype(np.int32),
                "reasons": fetched[1].astype(np.int32),
                "values": fetched[2],
            }
        self._convergence = out
        return out

    # -- scoring -------------------------------------------------------------

    def _fe_scores(self, s: _FeState, data: GameDataset, n_pad: int) -> Array:
        vbatch = data.device_shard(s.shard_name)
        scores = _fe_sweep_scorer()(vbatch, s.original_w())
        if scores.shape[1] > n_pad:
            scores = scores[:, :n_pad]
        elif scores.shape[1] < n_pad:
            scores = jnp.pad(scores, ((0, 0), (0, n_pad - scores.shape[1])))
        return scores

    def _re_training_scores(self, s: _ReState, n_pad: int) -> Array:
        scores = jnp.zeros((self.size, n_pad), jnp.float32)
        for table, eb, bucket in zip(s.tables, s.ebatches, s.red.buckets):
            scores = _re_sweep_scorer()(scores, table, eb, bucket.row_index)
        return scores

    def _re_scores_for(
        self, s: _ReState, data: GameDataset, n_pad: int
    ) -> Array:
        """All-lane RE scores on an ARBITRARY dataset: one host pass maps
        the dataset's entity values through the training vocabulary to
        (bucket, position); the per-config coefficient gather runs on
        device (no per-config host round trips)."""
        idc = data.id_columns.get(s.red.id_name)
        if idc is None:
            raise KeyError(
                f"dataset lacks id column '{s.red.id_name}' needed by "
                f"coordinate '{s.name}'"
            )
        codes = map_vocab_codes(s.vocab, idc.vocab[idc.codes])
        known = codes >= 0
        safe = np.where(known, codes, 0)
        row_bucket = np.where(known, s.red.entity_bucket[safe], -1)
        row_pos = np.where(known, s.red.entity_pos[safe], -1)

        batch = data.shard(s.red.shard_name)
        n = data.num_rows
        vals = np.asarray(batch.values)
        rows = np.asarray(batch.rows)
        cols = np.asarray(batch.cols)
        live = (vals != 0) & (rows < n)
        scores = jnp.zeros((self.size, n_pad), jnp.float32)
        for b_idx, (table, bucket) in enumerate(zip(s.tables, s.red.buckets)):
            sel = live & (row_bucket[np.minimum(rows, n - 1)] == b_idx)
            if not np.any(sel):
                continue
            part = np.nonzero(sel)[0]
            scores = _re_val_scorer()(
                scores,
                table,
                jnp.asarray(bucket.projection),
                jnp.asarray(vals[part], jnp.float32),
                jnp.asarray(rows[part], jnp.int32),
                jnp.asarray(row_pos[rows[part]], jnp.int32),
                jnp.asarray(cols[part], jnp.int32),
            )
        return scores

    def validation_scores(self, data: GameDataset) -> Array:
        """Raw model scores (no offsets) of EVERY config lane on ``data``
        as one [G, n_pad] device array."""
        n_pad = max(b.num_rows for b in data.feature_shards.values())
        total = jnp.zeros((self.size, n_pad), jnp.float32)
        for s in self._states.values():
            if isinstance(s, _FeState):
                total = total + self._fe_scores(s, data, n_pad)
            else:
                total = total + self._re_scores_for(s, data, n_pad)
        return total

    # -- model materialization ----------------------------------------------

    def model_for(self, g: int) -> GameModel:
        """The GAME model of config lane ``g`` (host slicing of the device
        tables; used once, for the selected winner)."""
        if not 0 <= g < self.size:
            raise IndexError(f"config index {g} out of range [0, {self.size})")
        models: dict = {}
        for name, s in self._states.items():
            if isinstance(s, _FeState):
                models[name] = FixedEffectModel(
                    coefficients=s.original_w()[g],
                    shard_name=s.shard_name,
                )
            else:
                buckets = tuple(
                    RandomEffectBucketModel(
                        coefficients=table[g],
                        projection=bucket.projection,
                        entity_codes=bucket.entity_codes,
                    )
                    for table, bucket in zip(s.tables, s.red.buckets)
                )
                models[name] = RandomEffectModel(
                    id_name=s.red.id_name,
                    shard_name=s.red.shard_name,
                    buckets=buckets,
                    entity_bucket=s.red.entity_bucket,
                    entity_pos=s.red.entity_pos,
                    vocab=s.vocab,
                )
        return GameModel(task=self.task, models=models)

    def emit_config_spans(
        self,
        metrics: Optional[np.ndarray] = None,
        metric_name: Optional[str] = None,
    ) -> None:
        conv = self.convergence()
        iterations = np.max(
            np.stack([c["iterations"] for c in conv.values()]), axis=0
        )
        # lane reason: the worst (unconverged-first) across coordinates
        reasons = None
        for c in conv.values():
            r = c["reasons"]
            reasons = r if reasons is None else np.where(
                (reasons == MAX_ITERATIONS) | (reasons == NOT_CONVERGED),
                reasons,
                r,
            )
        values = np.sum(np.stack([c["values"] for c in conv.values()]), axis=0)
        lams = self.lambdas
        first = next(iter(lams.values()))
        _emit_config_spans(
            first,
            lams,
            iterations,
            reasons,
            values=values,
            metrics=metrics,
            metric_name=metric_name,
        )


def _build_fe_state(name, c, data, G, lams, task):
    from photon_ml_tpu.data.normalization import (
        NormalizationType,
        build_normalization_context,
    )
    from photon_ml_tpu.data.stats import summarize

    c.optimizer.validate(task)
    norm = None
    if NormalizationType(c.normalization) != NormalizationType.NONE:
        summary = summarize(data.batch_for(c.shard_name))
        norm = build_normalization_context(
            NormalizationType(c.normalization),
            summary,
            intercept_index=c.intercept_index,
        )
        if c.optimizer.box_constraints:
            raise SweepUnsupportedError(
                f"coordinate '{name}': box constraints under normalization "
                "are not batched by the sweep path; use GameEstimator.fit"
            )
    if c.optimizer.down_sampling_rate < 1.0:
        raise SweepUnsupportedError(
            f"coordinate '{name}': down-sampling re-draws per update and is "
            "not batched by the sweep path; use GameEstimator.fit_grid"
        )
    batch = data.batch_for(c.shard_name).device()
    key_cfg = dataclasses.replace(c.optimizer, regularization_weight=0.0)
    l2s, l1s = split_reg_weights(c.optimizer.regularization, lams)
    constraints = c.optimizer.build_box_constraints(int(batch.num_features))
    base_obj = make_objective(
        task,
        factors=None if norm is None else norm.factors,
        shifts=None if norm is None else norm.shifts,
    )
    return _FeState(
        name=name,
        shard_name=c.shard_name,
        config=c.optimizer,
        lambdas=lams,
        batch=batch,
        l2s=l2s,
        l1s=l1s,
        constraints=constraints,
        normalization=norm,
        solver=_fe_sweep_solver(key_cfg, with_residual=True),
        W=jnp.zeros((G, int(batch.num_features)), jnp.float32),
    ), base_obj


def _build_re_state(name, c, data, G, lams, task) -> _ReState:
    from photon_ml_tpu.game.random_effect_data import (
        build_random_effect_dataset,
    )

    c.optimizer.validate(task)
    if c.projector != "index_map":
        raise SweepUnsupportedError(
            f"coordinate '{name}': projector '{c.projector}' is not batched "
            "by the sweep path (index_map only); use GameEstimator.fit_grid"
        )
    if c.optimizer.box_constraints:
        raise SweepUnsupportedError(
            f"coordinate '{name}': per-entity box constraints are not "
            "batched by the sweep path; use GameEstimator.fit_grid"
        )
    red = build_random_effect_dataset(
        data,
        c.id_name,
        c.shard_name,
        active_rows_per_entity=c.active_rows_per_entity,
        min_rows_per_entity=c.min_rows_per_entity,
        features_to_samples_ratio=c.features_to_samples_ratio,
    )
    if len(red.passive_rows):
        raise SweepUnsupportedError(
            f"coordinate '{name}': active-row caps leave passive rows, "
            "which the sweep scoring path does not batch; drop "
            "active_rows_per_entity or use GameEstimator.fit_grid"
        )
    key_cfg = dataclasses.replace(c.optimizer, regularization_weight=0.0)
    l2s, l1s = split_reg_weights(c.optimizer.regularization, lams)
    ebatches = tuple(b.entity_batch().device() for b in red.device_buckets())
    tables = [
        jnp.zeros((G, b.num_entities, b.num_local_features), jnp.float32)
        for b in red.buckets
    ]
    return _ReState(
        name=name,
        config=c.optimizer,
        lambdas=lams,
        red=red,
        ebatches=ebatches,
        l2s=l2s,
        l1s=l1s,
        solver=_re_sweep_solver(key_cfg),
        tables=tables,
        vocab=data.id_columns[c.id_name].vocab,
    )


def sweep_game(
    config,
    data: GameDataset,
    grid: SweepGrid,
    *,
    num_iterations: Optional[int] = None,
    warm_start: bool = True,
) -> GameSweepResult:
    """Run coordinate descent over ALL G configs simultaneously.

    ``config`` is a :class:`~photon_ml_tpu.game.estimator.GameConfig`;
    every coordinate must be a fixed-effect or an index-map random-effect
    block (:class:`SweepUnsupportedError` names anything else). The
    updating sequence and residual trick follow ``run_coordinate_descent``
    exactly, with every score/residual carrying the leading config axis.
    From the second CD iteration on, unconverged lanes warm-start from
    their more-regularized neighbor (:func:`path_warm_start`).
    """
    from photon_ml_tpu.game.estimator import (
        FixedEffectConfig,
        RandomEffectConfig,
    )

    G = grid.size
    if num_iterations is None:
        num_iterations = config.num_iterations
    states: dict = {}
    objs: dict = {}
    for name, c in config.coordinates.items():
        lams = grid.for_coordinate(name)
        if isinstance(c, FixedEffectConfig):
            states[name], objs[name] = _build_fe_state(
                name, c, data, G, lams, config.task
            )
        elif isinstance(c, RandomEffectConfig):
            states[name] = _build_re_state(name, c, data, G, lams, config.task)
            objs[name] = make_objective(config.task)
        else:
            raise SweepUnsupportedError(
                f"coordinate '{name}': {type(c).__name__} is not batched by "
                "the sweep path; use GameEstimator.fit_grid"
            )

    names = list(states)
    n_pad = max(b.num_rows for b in data.feature_shards.values())
    scores: dict[str, Array] = {
        name: jnp.zeros((G, n_pad), jnp.float32) for name in names
    }
    history: list[dict] = []
    total_steps = max(num_iterations * len(names), 1)
    telemetry.gauge("sweep.configs_total").set(G)
    telemetry.gauge("sweep.configs_done").set(0)

    result = GameSweepResult(config.task, states, history, n_pad)
    with telemetry.span(
        "sweep", task=config.task, configs=G, num_coordinates=len(names)
    ):
        for it in range(num_iterations):
            with telemetry.span("sweep_iteration", iteration=it):
                for idx, name in enumerate(names):
                    s = states[name]
                    with telemetry.span(
                        f"coordinate:{name}", iteration=it
                    ) as sp:
                        residual = None
                        if len(names) > 1:
                            residual = sum(
                                (scores[o] for o in names if o != name),
                                start=jnp.zeros_like(scores[name]),
                            )
                        if isinstance(s, _FeState):
                            _update_fe(s, objs[name], residual, it, warm_start)
                            scores[name] = result._fe_scores(s, data, n_pad)
                        else:
                            _update_re(s, objs[name], residual, it, warm_start)
                            scores[name] = result._re_training_scores(s, n_pad)
                        telemetry.sync_fetch(
                            scores[name][0, 0], label=f"sweep:{name}"
                        )
                        seconds = telemetry.trace.TRACER.now() - sp.ts
                        sp.set_attr(seconds=round(seconds, 6))
                    telemetry.counter("sweep.solves").inc(G)
                    step = it * len(names) + idx + 1
                    telemetry.gauge("sweep.configs_done").set(
                        int(G * step / total_steps)
                    )
                    history.append(
                        {
                            "iteration": it,
                            "coordinate": name,
                            "seconds": round(seconds, 6),
                            "configs": G,
                        }
                    )
    return result


def _update_fe(s: _FeState, obj, residual, it: int, warm_start: bool) -> None:
    G = len(s.lambdas)
    w0 = s.W
    if warm_start and it > 0 and s.reasons is not None:
        w0 = path_warm_start(s.W, s.reasons)
    if residual is None:
        residual = jnp.zeros((G, s.batch.num_rows), jnp.float32)
    res = s.solver(obj, s.batch, residual, w0, s.l2s, s.l1s, s.constraints)
    s.W = res.w
    s.reasons = res.reason
    s.iterations = res.iterations
    s.values = res.value


def _update_re(s: _ReState, obj, residual, it: int, warm_start: bool) -> None:
    G = len(s.lambdas)
    lane_un = None
    iters_parts = []
    values_parts = []
    for i, (eb, bucket) in enumerate(zip(s.ebatches, s.red.buckets)):
        if residual is not None:
            extra = _re_residual_gather()(residual, bucket.row_index)
        else:
            extra = jnp.zeros(
                (G,) + tuple(bucket.row_index.shape), jnp.float32
            )
        w0 = s.tables[i]
        if warm_start and it > 0 and s.reasons is not None:
            w0 = path_warm_start(w0, s.reasons)
        res = s.solver(obj, eb, extra, w0, s.l2s, s.l1s)
        s.tables[i] = res.w
        un = _lane_unconverged(res.reason)
        lane_un = un if lane_un is None else (lane_un | un)
        iters_parts.append(jnp.max(res.iterations, axis=1))
        values_parts.append(jnp.sum(res.value, axis=1))
    # lane-level aggregates: worst reason, max iterations, summed values
    s.reasons = jnp.where(
        lane_un,
        jnp.int32(MAX_ITERATIONS),
        jnp.int32(FUNCTION_VALUES_CONVERGED),
    )
    s.iterations = jnp.max(jnp.stack(iters_parts), axis=0)
    s.values = jnp.sum(jnp.stack(values_parts), axis=0)
