"""On-device hyperparameter sweeps: vmapped multi-λ training, warm-started
regularization paths, and best-model selection (ROADMAP item 5).

- :mod:`photon_ml_tpu.sweep.grid` — the ``lambda=1e-4:1e2:log16`` spec
  grammar with per-coordinate overrides, descending path order, and typed
  parse errors.
- :mod:`photon_ml_tpu.sweep.runner` — G configs batched into single
  ``instrumented_jit`` executables (the config axis composes with the
  per-entity vmap lane on random-effect buckets), with unconverged lanes
  warm-started from their more-regularized neighbor.
- :mod:`photon_ml_tpu.sweep.select` — one vmapped evaluator pass over all
  lanes, NaN-safe selection policies, and ``publish_version`` export of
  the winner into the serving registry.
"""

from photon_ml_tpu.sweep.grid import (  # noqa: F401
    SweepGrid,
    SweepSpecError,
    parse_sweep_spec,
)
from photon_ml_tpu.sweep.runner import (  # noqa: F401
    GameSweepResult,
    GlmSweepResult,
    SweepUnsupportedError,
    path_warm_start,
    sweep_game,
    sweep_glm,
)
from photon_ml_tpu.sweep.select import (  # noqa: F401
    SweepSelection,
    SweepSelectionError,
    default_metric,
    evaluate_sweep,
    export_winner,
    run_selection,
    select_best,
)
