"""Benchmark: sparse logistic GLM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config #1 from BASELINE.md: L2 logistic regression, 1M x 10K sparse
(~20 nnz/row). Metric = example-rows processed per second per chip, where
rows processed = n_rows x (number of full-data objective passes: one
value+grad per LBFGS iteration + the initial evaluation; margin-space line
search trials are O(rows) elementwise and excluded). The reference publishes
no numbers (BASELINE.json "published": {}), so vs_baseline is null until a
measured Spark baseline exists.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import LBFGSConfig, glm_adapter, lbfgs_solve

    n_rows = 1_000_000
    n_features = 10_000
    nnz_per_row = 20
    max_iters = 20

    rng = np.random.default_rng(0)
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    # labels from a planted model so the optimizer does real work
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float64)

    # Tiled one-hot-matmul layout: the pallas fast path (ops/tiled.py);
    # round-1's padded-COO SparseBatch path measured ~850K rows/s here.
    batch = TiledBatch.from_coo(
        values=values, rows=rows, cols=cols, labels=y, num_features=n_features
    )
    obj = make_objective("logistic", l2_weight=1.0)
    cfg = LBFGSConfig(max_iterations=max_iters, tolerance=0.0)  # fixed work

    def run(w0, batch):
        # batch enters as a jit argument (not a closure constant: captured
        # arrays are embedded in the compile request, which the axon tunnel
        # rejects at this size with HTTP 413).
        return lbfgs_solve(glm_adapter(obj, batch), w0, cfg)

    run_jit = jax.jit(run)

    # compile + warmup with a DIFFERENT w0 than the timed run: identical
    # (fn, args) re-executions are result-cached on the tunnel TPU, and
    # block_until_ready is a no-op there — a scalar fetch inside the timed
    # window is the only true sync (PERF_NOTES.md).
    w_warm = jnp.asarray(rng.normal(size=n_features) * 1e-3, jnp.float32)
    float(run_jit(w_warm, batch).value)

    w0 = jnp.zeros((n_features,), jnp.float32)
    t0 = time.perf_counter()
    res = run_jit(w0, batch)
    final_value = float(res.value)  # forces execution + D2H sync
    elapsed = time.perf_counter() - t0

    iters = int(res.iterations)
    passes = iters + 1  # init value_and_grad + one per iteration
    rows_per_sec = n_rows * passes / elapsed

    print(
        json.dumps(
            {
                "metric": "glm_logistic_1Mx10K_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": None,
                "detail": {
                    "elapsed_s": round(elapsed, 3),
                    "lbfgs_iterations": iters,
                    "final_loss": final_value,
                    "platform": jax.devices()[0].platform,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
