"""Benchmark driver: ALL FIVE BASELINE.md configs + aux throughput lines.

Prints one JSON line per metric ({"metric", "value", "unit",
"vs_baseline"}), headline first:

  1. glm_logistic_1Mx10K_rows_per_sec_per_chip   (config #1, inline)
     + tiled_layout_build_rows_per_sec           (host layout build)
  2. linreg_tron_1Mx10K_rows_per_sec_per_chip    (config #2, bench_suite)
     + linreg_owlqn_elasticnet_...               (elastic-net variant)
  3. poisson_offsets_box_1Mx10K_rows_per_sec...  (config #3, bench_suite)
  4. glmix_fe_re_logistic_1Mx100Kusers_coeffs... (config #4, bench_game)
  5. game_1B_coeffs_trained_per_sec              (config #5, bench_scale)
  +  multichip_* scaling efficiency at 1 vs 8 devices (bench_multichip)
  +  avro_ingest_rows_per_sec                    (bench_ingest)

Sub-benchmarks run as subprocesses (fresh jit caches, bounded memory); a
failing sub-benchmark emits an {"metric": ..., "error": ...} line instead
of killing the run. The reference publishes no numbers (BASELINE.json
"published": {}), so vs_baseline is null throughout.

PHOTON_BENCH_BUDGET_S caps the whole run's wall clock: once spent, the
remaining sub-benchmarks are skipped but every expected metric still
emits a valid JSON line with "truncated": true (no more silent rc=124 —
the BENCH_r05 failure mode). With PHOTON_TRACE_OUT set, a run report
(markdown + JSON baseline) is written beside the trace at the end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import LBFGSConfig, glm_adapter, lbfgs_solve

    # spans/metrics opt in via PHOTON_TRACE_OUT / PHOTON_TELEMETRY_OUT; the
    # snapshot below rides the bench JSON either way (one shared schema)
    telemetry.configure_from_env()
    # profile EVERY dispatch: the bench is a handful of dispatches (the
    # 1/N sampling default exists for hour-long fits), and the per-kernel
    # MFU / hot-dispatch-fraction lines below need the timed dispatch
    # itself honestly measured, not extrapolated from warmup
    telemetry.profile.set_sample_every(1)
    # an armed PHOTON_FAULT_PLAN would corrupt the bench numbers silently
    # (injected stalls/errors read as regressions) — same loud warning the
    # train/serve drivers give
    from photon_ml_tpu import faults

    faults.warn_if_armed()

    n_rows = 1_000_000
    n_features = 10_000
    nnz_per_row = 20
    max_iters = 20

    rng = np.random.default_rng(0)
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    # labels from a planted model so the optimizer does real work
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float64)

    # Tiled one-hot-matmul layout: the pallas fast path (ops/tiled.py);
    # round-1's padded-COO SparseBatch path measured ~850K rows/s here.
    # The one-time host layout build is reported as its own metric (it is
    # excluded from the steady-state training throughput below).
    t0 = time.perf_counter()
    batch = TiledBatch.from_coo(
        values=values, rows=rows, cols=cols, labels=y, num_features=n_features
    )
    t_layout = time.perf_counter() - t0
    obj = make_objective("logistic", l2_weight=1.0)
    cfg = LBFGSConfig(max_iterations=max_iters, tolerance=0.0)  # fixed work

    def run(w0, batch):
        # batch enters as a jit argument (not a closure constant: captured
        # arrays are embedded in the compile request, which the axon tunnel
        # rejects at this size with HTTP 413).
        return lbfgs_solve(glm_adapter(obj, batch), w0, cfg)

    # accounted jit (telemetry.xla): the headline's compile time, FLOPs
    # and bytes-accessed land in the executable registry for the detail
    run_jit = telemetry.instrumented_jit(run, name="bench_lbfgs")

    # compile + warmup with a DIFFERENT w0 than the timed run: identical
    # (fn, args) re-executions are result-cached on the tunnel TPU, and
    # block_until_ready is a no-op there — a scalar fetch inside the timed
    # window is the only true sync (PERF_NOTES.md).
    w_warm = jnp.asarray(rng.normal(size=n_features) * 1e-3, jnp.float32)
    float(run_jit(w_warm, batch).value)

    w0 = jnp.zeros((n_features,), jnp.float32)
    t0 = time.perf_counter()
    with telemetry.span("bench_lbfgs", rows=n_rows, features=n_features):
        res = run_jit(w0, batch)
        # forces execution + D2H sync, through the accounted fetch point
        final_value = float(telemetry.sync_fetch(res.value, label="loss"))
    elapsed = time.perf_counter() - t0

    iters = int(res.iterations)
    passes = int(res.data_passes)  # init eval + one per iteration (LBFGS)
    rows_per_sec = n_rows * passes / elapsed

    # roofline detail: per-solve cost analysis + achieved-vs-peak numbers
    # (None = "unknown": backends without cost analysis / unknown peaks)
    rec = run_jit.record_for(w0, batch)
    peak_flops, peak_bw = telemetry.xla.device_peaks()
    device_util = {
        "flops_per_solve": None if rec is None else rec.flops,
        "bytes_accessed_per_solve": None if rec is None else rec.bytes_accessed,
        "compile_seconds": None if rec is None else round(rec.compile_seconds, 3),
        "mfu": (
            round(rec.flops / (elapsed * peak_flops), 6)
            if rec is not None and rec.flops and peak_flops
            else None
        ),
        "bandwidth_utilization": (
            round(rec.bytes_accessed / (elapsed * peak_bw), 6)
            if rec is not None and rec.bytes_accessed and peak_bw
            else None
        ),
    }
    layout_line = json.dumps(
        {
            "metric": "tiled_layout_build_rows_per_sec",
            "value": round(n_rows / t_layout, 1),
            "unit": "rows/s",
            "vs_baseline": None,
            "detail": {"seconds": round(t_layout, 2), "nnz": nnz},
        }
    )

    print(
        json.dumps(
            {
                "metric": "glm_logistic_1Mx10K_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": None,
                "detail": {
                    "elapsed_s": round(elapsed, 3),
                    "lbfgs_iterations": iters,
                    "final_loss": final_value,
                    "platform": jax.devices()[0].platform,
                    "device": str(jax.devices()[0]),
                    # same schema as TrainingFinishEvent.metrics_snapshot /
                    # --telemetry-out: fetch + compile accounting for the run
                    "telemetry": telemetry.snapshot()["counters"],
                    "device_utilization": device_util,
                },
            }
        ),
        flush=True,
    )
    # the layout-build rate prints AFTER the headline: harness consumers
    # take the first metric line as the training-throughput headline
    print(layout_line, flush=True)

    # executable-level utilization (telemetry.profile): the headline
    # solve's sampled honest timings → per-kernel MFU and the fraction of
    # the timed window actually spent inside the profiled executable.
    # Null values stay null ("unknown": no cost analysis / no known
    # device peak) — the gate skips them rather than gating a fake 0.
    prof = telemetry.profile.merged_profiles(names=("bench_lbfgs",)).get(
        "bench_lbfgs"
    )
    mfu = None if prof is None else prof.get("mfu")
    hot_fraction = None
    if (
        prof is not None
        and prof.get("mean_dispatch_seconds")
        and elapsed > 0
    ):
        hot_fraction = round(
            min(prof["mean_dispatch_seconds"] / elapsed, 1.0), 6
        )
    for metric, value in (
        ("glm_value_grad_mfu", mfu),
        ("hot_dispatch_fraction", hot_fraction),
    ):
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": "fraction",
                    "vs_baseline": None,
                    "detail": {"executable": "bench_lbfgs",
                               "profile": prof},
                }
            ),
            flush=True,
        )


#: The metric lines main() itself prints (config #1 + the layout build +
#: the profiled per-kernel utilization pair).
HEADLINE_METRICS = (
    "glm_logistic_1Mx10K_rows_per_sec_per_chip",
    "tiled_layout_build_rows_per_sec",
    "glm_value_grad_mfu",
    "hot_dispatch_fraction",
)


def run_headline(deadline=None):
    """Config #1: in-process when uncapped; under a budget it runs as a
    killable ``bench.py --headline-only`` subprocess capped at the
    remaining budget, so a budget expiring MID-solve still ends in
    truncated lines + exit 0 instead of the outer timeout's rc=124 (the
    in-process jax solve cannot be preempted)."""
    if deadline is None:
        main()
        return
    from bench_suite import truncated_line

    emitted = set()
    remaining = deadline - time.monotonic()
    failure = None  # non-budget failure: report an error, not "truncated"
    if remaining > 0:
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "bench.py"),
                 "--headline-only"],
                capture_output=True,
                text=True,
                timeout=max(remaining - 5.0, 1.0),
                cwd=here,
            )
            out = proc.stdout
            if proc.returncode != 0:
                failure = f"rc={proc.returncode}: {proc.stderr[-400:]}"
        except subprocess.TimeoutExpired as e:
            out = e.stdout or ""  # budget cap: truncation, not an error
        except (subprocess.SubprocessError, OSError) as e:
            out = ""
            failure = str(e)[-400:]
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("{"):
                print(line, flush=True)
                emitted.add(_metric_of(line))
        if failure is None and remaining > 60 and not emitted:
            # plenty of budget yet nothing printed: a crash, not a skip
            failure = "headline produced no metrics"
    if failure is not None:
        # a crashed headline must look like an ERROR, never like a
        # budget skip (same contract as run_sub_benchmarks)
        print(
            json.dumps(
                {"metric": "bench_headline", "value": None, "unit": None,
                 "vs_baseline": None, "error": failure}
            ),
            flush=True,
        )
        return
    for metric in HEADLINE_METRICS:
        if metric not in emitted:
            print(truncated_line(metric), flush=True)


from bench_suite import SUITE_METRICS as _SUITE_METRICS

#: Expected metric lines per sub-benchmark, so a budget-skipped script
#: still emits one valid truncated line PER metric it would have printed.
#: bench_suite's names come from its own module — one source of truth.
from bench_diagnostics import DIAGNOSTICS_METRICS as _DIAGNOSTICS_METRICS
from bench_freshness import FRESHNESS_METRICS as _FRESHNESS_METRICS
from bench_ingest import INGEST_METRICS as _INGEST_METRICS
from bench_multichip import MULTICHIP_METRICS as _MULTICHIP_METRICS
from bench_overlap import OVERLAP_METRICS as _OVERLAP_METRICS
from bench_sweep import SWEEP_METRICS as _SWEEP_METRICS

_SCRIPT_METRICS = {
    "bench_suite.py": _SUITE_METRICS,
    "bench_game.py": ("glmix_fe_re_logistic_1Mx100Kusers_coeffs_per_sec",),
    "bench_scale.py": ("game_1B_coeffs_trained_per_sec",),
    "bench_multichip.py": _MULTICHIP_METRICS,
    "bench_sweep.py": _SWEEP_METRICS,
    "bench_overlap.py": _OVERLAP_METRICS,
    "bench_ingest.py": _INGEST_METRICS,
    "bench_freshness.py": _FRESHNESS_METRICS,
    "bench_diagnostics.py": _DIAGNOSTICS_METRICS,
    "bench_serving.py": ("serving_p50_ms", "serving_p99_ms",
                         "serving_rows_per_sec",
                         "serving_fleet_p99_resize_ratio",
                         "serving_fleet_kill_recovery_s"),
    "bench_northstar.py": ("north_star_e2e",),
}


def run_sub_benchmarks(deadline=None):
    """Forward the JSON lines of every sub-benchmark (configs #2-#5 +
    ingestion + the north-star e2e pipeline), each in its own process.

    ``deadline`` (monotonic seconds, from PHOTON_BENCH_BUDGET_S): scripts
    that would start past it are skipped with truncated placeholder lines,
    and a running script's timeout is capped at the remaining budget —
    metrics it printed before the cap are forwarded, the rest truncated.
    """
    from bench_suite import truncated_line

    here = os.path.dirname(os.path.abspath(__file__))
    # north-star (20M-row full pipeline) runs last and longest; the
    # driver's BASELINE numbers come from the earlier lines either way
    for script in ("bench_suite.py", "bench_game.py", "bench_scale.py",
                   "bench_multichip.py", "bench_sweep.py",
                   "bench_overlap.py", "bench_ingest.py",
                   "bench_freshness.py", "bench_diagnostics.py",
                   "bench_serving.py",
                   "bench_northstar.py"):
        path = os.path.join(here, script)
        expected = _SCRIPT_METRICS.get(script, (script.replace(".py", ""),))
        remaining = (
            None if deadline is None else deadline - time.monotonic()
        )
        if remaining is not None and remaining <= 0:
            for metric in expected:
                print(truncated_line(metric), flush=True)
            continue
        timeout = 1500 if script != "bench_northstar.py" else 4500
        budget_capped = False
        if remaining is not None:
            # keep a kill grace INSIDE the remaining budget: the deadline
            # is the flush-by time (bench_suite.budget_deadline already
            # excludes the exit margin), so the subprocess must be dead —
            # including the kill escalation — with seconds to spare for
            # forwarding its partial output and the truncated lines
            capped = max(remaining - 5.0, 1.0)
            if capped < timeout:
                timeout = capped
                budget_capped = True
        emitted = set()
        try:
            proc = subprocess.run(
                [sys.executable, path],
                capture_output=True,
                text=True,
                timeout=timeout,
                cwd=here,
            )
            for line in proc.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    print(line, flush=True)
                    emitted.add(_metric_of(line))
            if proc.returncode != 0 or not emitted:
                raise RuntimeError(
                    f"rc={proc.returncode}: {proc.stderr[-400:]}"
                )
        except (subprocess.SubprocessError, RuntimeError, OSError) as e:
            # a timed-out sub-benchmark may have emitted metrics already —
            # forward them before the error/truncated lines
            partial = getattr(e, "stdout", None) or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in partial.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    print(line, flush=True)
                    emitted.add(_metric_of(line))
            over_budget = deadline is not None and (
                time.monotonic() >= deadline
                or (
                    budget_capped
                    and isinstance(e, subprocess.TimeoutExpired)
                )
            )
            if over_budget:
                # the budget, not the benchmark, ended this script: emit
                # valid truncated lines for whatever it never printed
                for metric in expected:
                    if metric not in emitted:
                        print(truncated_line(metric), flush=True)
            else:
                print(
                    json.dumps(
                        {"metric": script.replace(".py", ""), "value": None,
                         "unit": None, "vs_baseline": None,
                         "error": str(e)[-400:]}
                    ),
                    flush=True,
                )


def _metric_of(json_line: str):
    try:
        return json.loads(json_line).get("metric")
    except json.JSONDecodeError:
        return None


def write_run_report():
    """With PHOTON_TRACE_OUT set, render this process's telemetry as a run
    report beside the trace (markdown + JSON compare baseline for the
    bench_suite --gate / cli report --compare flows).

    Sub-benchmarks inherit the same env var, and the last one to run
    (bench_northstar.py, the e2e whose silence motivated this layer) owns
    both the trace file and its report — never overwrite it with the
    parent's glm-only telemetry; only fill in the report when no
    sub-benchmark produced one."""
    trace_out = os.environ.get("PHOTON_TRACE_OUT")
    if not trace_out:
        return
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.report import RunReport, report_path

    # same per-member suffixing the trace sink applied: in a fleet each
    # process owns its report instead of last-writer-winning one file
    md_path = report_path(telemetry.member_artifact_path(trace_out))
    if os.path.exists(md_path):
        print(f"run report (from sub-benchmark): {md_path}", file=sys.stderr)
        return
    report = RunReport.from_live()
    with open(md_path, "w", encoding="utf-8") as fh:
        fh.write(report.to_markdown())
    report.save_json(md_path[: -len(".md")] + ".json")
    print(f"run report: {md_path}", file=sys.stderr)


if __name__ == "__main__":
    from bench_suite import budget_deadline

    if "--headline-only" in sys.argv:
        # subprocess mode for run_headline: just config #1, no recursion
        main()
        sys.exit(0)
    _deadline = budget_deadline()
    run_headline(deadline=_deadline)
    run_sub_benchmarks(deadline=_deadline)
    write_run_report()
