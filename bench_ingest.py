"""Benchmark: Avro ingestion throughput (host side).

Measures :func:`photon_ml_tpu.data.avro.read_game_dataset_from_avro` on a
TrainingExampleAvro file generated at bench time — the end-to-end rate a
training driver sees (native C++ block decode + index-map build + COO ->
padded SparseBatch + device upload), plus the pure array-decode rate of
the native path alone (native/avro_decode.cpp).

Reference analog: AvroDataReader.scala:87-237 spreads this work over a
Spark cluster; here one host core decodes ~0.5-1M rows/s (~40x the pure
Python schema-walking decoder, which remains the fallback path).

Prints one JSON line (the decode + end-to-end rates ride in detail).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

# Ingestion is HOST-side work; measure it against host memory. (On this
# rig the TPU is behind a ~26 MB/s tunnel, so eager jnp uploads of the
# COO arrays would measure the link, not the reader — a real PCIe-attached
# chip moves the same arrays in ~0.1 s.)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    from photon_ml_tpu.data.avro import (
        TRAINING_EXAMPLE_AVRO,
        read_game_dataset_from_avro,
        write_avro,
    )
    from photon_ml_tpu.data.avro_native import read_game_arrays_native

    n, d, k = 400_000, 10_000, 15
    rng = np.random.default_rng(0)
    cols = rng.integers(0, d, size=(n, k))
    vals = rng.normal(size=(n, k))
    y = rng.integers(0, 2, size=n)
    users = rng.integers(0, 5000, size=n)

    def recs():
        for i in range(n):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"f{cols[i, j]}", "term": "",
                     "value": float(vals[i, j])}
                    for j in range(k)
                ],
                "metadataMap": {"userId": str(users[i])},
                "weight": None,
                "offset": None,
            }

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.avro")
        t0 = time.perf_counter()
        write_avro(path, TRAINING_EXAMPLE_AVRO, recs())
        t_write = time.perf_counter() - t0
        size_mb = os.path.getsize(path) / 2**20

        # host-side columnar decode alone (no dataset assembly/upload)
        t0 = time.perf_counter()
        arrays = read_game_arrays_native(
            [path], {"features": ("features",)}, None, ("userId",)
        )
        t_decode = time.perf_counter() - t0
        native_ok = arrays is not None

        t0 = time.perf_counter()
        ds = read_game_dataset_from_avro(path, id_columns=("userId",))
        t_first = time.perf_counter() - t0
        assert ds.num_rows == n
        # steady-state rate: the first call pays one-time XLA compiles in
        # the SparseBatch padding path
        t0 = time.perf_counter()
        ds = read_game_dataset_from_avro(path, id_columns=("userId",))
        t_full = time.perf_counter() - t0

        print(
            json.dumps(
                {
                    "metric": "avro_ingest_rows_per_sec",
                    "value": round(n / t_full, 1),
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "detail": {
                        "rows": n,
                        "nnz_per_row": k,
                        "file_mb": round(size_mb, 1),
                        "decode_rows_per_sec": (
                            round(n / t_decode, 1) if native_ok else None
                        ),
                        "native_decoder": native_ok,
                        "end_to_end_seconds": round(t_full, 3),
                        "first_call_seconds": round(t_first, 3),
                        "write_seconds": round(t_write, 3),
                    },
                }
            )
        )


if __name__ == "__main__":
    main()
