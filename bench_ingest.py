"""Benchmark: Avro ingestion throughput (host side) + the ingest pipeline.

Three JSON lines:

  avro_ingest_rows_per_sec      — the ONE-SHOT reader a training driver
      used to see (native C++ block decode + index-map build + COO ->
      padded SparseBatch + upload). Detail carries a decode-thread
      scaling probe: the pure array-decode rate at threads=1 vs one
      thread per host core (``read_game_arrays_native(threads=)``).
  ingest_pipeline_rows_per_sec  — the NEW end-to-end path: the
      photon_ml_tpu.ingest ChunkStream (file-split planner -> parallel
      block decode into the staging ring -> double-buffered upload ->
      device-side assembly). Detail reports the speedup over the
      one-shot reader measured in the SAME run on the SAME host — the
      acceptance target is >= 5x.

Reference analog: AvroDataReader.scala:87-237 spreads this work over a
Spark cluster; here the decode workers are host threads.

Budget: ``PHOTON_BENCH_BUDGET_S`` is honored — phases starting past the
deadline emit valid ``{"metric": ..., "truncated": true}`` lines instead
of silence, like the rest of the suite.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

INGEST_METRICS = (
    "avro_ingest_rows_per_sec",
    "ingest_pipeline_rows_per_sec",
)


def _on_cpu() -> bool:
    """Whether the pipeline's device side actually ran on CPU (the live
    backend, not the env — bench_suite --ingest runs in-process on a
    possibly-TPU-initialized jax)."""
    import jax

    return jax.devices()[0].platform == "cpu"


def _write_shards(tmp: str, n: int, d: int, k: int, n_shards: int):
    """Generate TrainingExampleAvro shard files via the columnar fast
    writer (the python per-record writer spent ~29-48 s here in r04/r05
    and measured the generator, not ingestion)."""
    from photon_ml_tpu.data.avro import write_training_examples_fast

    rng = np.random.default_rng(0)
    names = [f"f{j}" for j in range(d)]
    paths = []
    per = n // n_shards
    for s in range(n_shards):
        rows = per if s < n_shards - 1 else n - per * (n_shards - 1)
        cols = rng.integers(0, d, size=(rows, k)).astype(np.int32)
        vals = rng.normal(size=(rows, k))
        y = rng.integers(0, 2, size=rows).astype(np.float64)
        users = rng.integers(0, 5000, size=rows)
        starts = np.arange(rows + 1, dtype=np.int64) * k
        path = os.path.join(tmp, f"shard-{s:02d}.avro")
        write_training_examples_fast(
            path,
            y,
            {"features": (starts, cols.reshape(-1), vals.reshape(-1))},
            names,
            {"userId": (users.astype(np.int64),
                        [str(u) for u in range(5000)])},
            block_records=4096,
        )
        paths.append(path)
    return paths


def run_ingest(deadline=None) -> dict[str, float | None]:
    """Run both metrics (budget-aware); returns {metric: value-or-None}
    for the ``bench_suite --gate`` flow."""
    from bench_suite import truncated_line

    results: dict[str, float | None] = {}
    if deadline is not None and time.monotonic() > deadline:
        for m in INGEST_METRICS:
            print(truncated_line(m), flush=True)
            results[m] = None
        return results

    from photon_ml_tpu.data.avro import (
        build_index_maps_from_avro,
        read_game_dataset_from_avro,
    )
    from photon_ml_tpu.data.avro_native import read_game_arrays_native
    from photon_ml_tpu.ingest import IngestSpec, read_game_dataset_streamed

    n, d, k = 400_000, 10_000, 15
    cores = os.cpu_count() or 1
    tmp_ctx = tempfile.TemporaryDirectory()
    with tmp_ctx as tmp:
        t0 = time.perf_counter()
        paths = _write_shards(tmp, n, d, k, n_shards=4)
        t_write = time.perf_counter() - t0
        size_mb = sum(os.path.getsize(p) for p in paths) / 2**20

        # -- decode-thread scaling probe (array decode only) --------------
        decode_scaling = {}
        for threads in (1, cores):
            t0 = time.perf_counter()
            arrays = read_game_arrays_native(
                paths, {"features": ("features",)}, None, ("userId",),
                threads=threads,
            )
            if arrays is None:
                decode_scaling = {"native_decoder": False}
                break
            decode_scaling[f"threads_{threads}"] = round(
                n / (time.perf_counter() - t0), 1
            )
        native_ok = decode_scaling.get("native_decoder", True)

        # -- metric 1: the one-shot reader --------------------------------
        t0 = time.perf_counter()
        ds = read_game_dataset_from_avro(paths, id_columns=("userId",))
        t_first = time.perf_counter() - t0
        assert ds.num_rows == n
        # steady-state rate: the first call pays one-time XLA compiles in
        # the SparseBatch padding path
        t0 = time.perf_counter()
        ds = read_game_dataset_from_avro(paths, id_columns=("userId",))
        t_oneshot = time.perf_counter() - t0
        oneshot_rate = n / t_oneshot
        results["avro_ingest_rows_per_sec"] = round(oneshot_rate, 1)
        print(
            json.dumps(
                {
                    "metric": "avro_ingest_rows_per_sec",
                    "value": round(oneshot_rate, 1),
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "detail": {
                        "rows": n,
                        "nnz_per_row": k,
                        "shard_files": len(paths),
                        "file_mb": round(size_mb, 1),
                        "native_decoder": native_ok,
                        "decode_rows_per_sec": decode_scaling or None,
                        "host_cores": cores,
                        "end_to_end_seconds": round(t_oneshot, 3),
                        "first_call_seconds": round(t_first, 3),
                        "write_seconds": round(t_write, 3),
                    },
                }
            ),
            flush=True,
        )

        if deadline is not None and time.monotonic() > deadline:
            print(truncated_line("ingest_pipeline_rows_per_sec"),
                  flush=True)
            results["ingest_pipeline_rows_per_sec"] = None
            return results

        # -- metric 2: the ingest pipeline --------------------------------
        # production mode: the feature space is pinned up front (the
        # cheap vocab-only scan; persisted index maps in a real run)
        t0 = time.perf_counter()
        index_maps = build_index_maps_from_avro(
            paths, {"features": ("features",)}
        )
        t_index = time.perf_counter() - t0
        spec = IngestSpec(workers=cores, chunk_rows=50_000,
                          nnz_per_row_hint=k + 2)
        # warm the assembler/writer executables on a small prefix so the
        # timed run measures the pipeline, not one-time XLA compiles
        read_game_dataset_streamed(
            paths[:1], index_maps=index_maps, id_columns=("userId",),
            spec=spec,
        )
        t0 = time.perf_counter()
        ds2 = read_game_dataset_streamed(
            paths, index_maps=index_maps, id_columns=("userId",),
            spec=spec,
        )
        t_pipe = time.perf_counter() - t0
        assert ds2.num_rows == n
        pipe_rate = n / t_pipe
        results["ingest_pipeline_rows_per_sec"] = round(pipe_rate, 1)
        from photon_ml_tpu import telemetry

        snap = telemetry.snapshot()
        counters = snap.get("counters", {})
        print(
            json.dumps(
                {
                    "metric": "ingest_pipeline_rows_per_sec",
                    "value": round(pipe_rate, 1),
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "detail": {
                        "rows": n,
                        "workers": cores,
                        "chunk_rows": spec.chunk_rows,
                        "prefetch_depth": spec.prefetch_depth,
                        "seconds": round(t_pipe, 3),
                        "index_build_seconds": round(t_index, 3),
                        "speedup_over_oneshot": round(
                            pipe_rate / oneshot_rate, 2
                        ),
                        "stalls": counters.get("ingest.stalls", 0),
                        "buffer_growths": counters.get(
                            "ingest.buffer_growths", 0
                        ),
                        "native_decoder": native_ok,
                        "simulated": _on_cpu(),
                    },
                }
            ),
            flush=True,
        )
    return results


def main():
    # Standalone runs measure ingestion against HOST memory. (On this rig
    # the TPU is behind a ~26 MB/s tunnel, so eager uploads of the COO
    # arrays would measure the link, not the reader.) Set here, NOT at
    # module scope: bench.py imports INGEST_METRICS from this module and
    # an import-time setdefault would silently force the whole driver —
    # and every subprocess sub-benchmark — onto CPU.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from bench_suite import budget_deadline

    run_ingest(deadline=budget_deadline())


if __name__ == "__main__":
    main()
